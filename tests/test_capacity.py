"""Capacity plane (ISSUE 14): the spot-fleet capstone drill.

A training gang and a serve deployment share ONE autoscaled spot
cluster whose worker nodes exist only because the CapacityAutoscaler
aggregated their demand (gang bundles, replica actors) and launched
them. Scheduled preemptions with warning windows then hit the fleet:
the drill asserts that replacement capacity is pre-provisioned BEFORE
the preempted node dies (`preempt.announced` → `autoscaler.replace` →
`node.dead` in the postmortem timeline), that training finishes with
`max_failures=0` (only `num_preempt_restarts` consumed), and that
serve never surfaces an untyped error to callers during the episode.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.core.capacity import (
    CapacityAutoscaler,
    FakeNodeProvider,
    NodeType,
    SpotNodeProvider,
)
from ray_tpu.core.exceptions import RayTpuError
from ray_tpu.util.events import events


def _first(evs, kind, **match):
    for e in evs:
        if e.get("kind") != kind:
            continue
        extra = e.get("extra") or {}
        if all(extra.get(k) == v for k, v in match.items()):
            return e
    raise AssertionError(
        f"no {kind} event matching {match} in "
        f"{[(e.get('kind'), e.get('extra')) for e in evs]}"
    )


def test_spot_fleet_capstone(tmp_path):
    """Train + serve on an autoscaled spot fleet survive an announced
    preemption: replacement first, death second, zero failure budget
    burned, one reconstructable postmortem bundle."""
    from ray_tpu import serve
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )
    from ray_tpu.util import state
    from ray_tpu.util.metrics import registry
    from ray_tpu.util.postmortem import load_bundle

    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    scaler = None
    try:
        events().clear()
        provider = SpotNodeProvider(FakeNodeProvider(rt.scheduler),
                                    warning_s=3.0)
        # distinct custom resources keep the two workloads on their own
        # node types, so demand aggregation (not luck) decides the fleet
        scaler = CapacityAutoscaler(
            rt.scheduler, provider,
            [
                NodeType("spot-train", {"CPU": 1.0, "trainer": 1.0},
                         capacity_class="spot"),
                NodeType("spot-serve", {"CPU": 2.0, "serve_slot": 2.0},
                         capacity_class="spot"),
            ],
            poll_interval_s=0.05, idle_timeout_s=60.0, runtime=rt,
        )
        scaler.start()

        # ---- serve side: 2 replicas that only a scaled-up node can host
        @serve.deployment(num_replicas=2,
                          resources_per_replica={"CPU": 1.0,
                                                 "serve_slot": 1.0})
        class Echo:
            def __call__(self, x):
                return f"ok-{x}"

        handle = serve.run(Echo.bind(), name="fleet-echo")
        assert ray_tpu.get(handle.remote(0), timeout=60) == "ok-0"

        # ---- train side: a 2-worker gang, one worker per spot node
        def train_fn(config):
            from ray_tpu import train

            ctx = train.get_context()
            ckpt = train.get_checkpoint()
            start = int(ckpt["step"]) + 1 if ckpt is not None else 0
            for step in range(start, 30):
                time.sleep(0.02)
                if ctx.world_rank != 0:
                    if train.is_preempted():
                        return "preempted"
                    continue
                if train.should_checkpoint():
                    train.report({"step": step}, checkpoint={"step": step},
                                 checkpoint_step=step)
                elif train.is_preempted():
                    return "preempted"
                elif step % 10 == 9:
                    train.report({"step": step}, checkpoint={"step": step},
                                 checkpoint_step=step)
                else:
                    train.report({"step": step})
            return "done"

        controller = TrainController(
            train_fn,
            ScalingConfig(num_workers=2,
                          resources_per_worker={"CPU": 1.0, "trainer": 1.0}),
            RunConfig(name="spot-fleet",
                      storage_path=str(tmp_path / "trial"),
                      failure=FailureConfig(max_failures=0)),
            train_config={},
            restart_backoff_s=0.0,
        )
        box = {}
        thread = threading.Thread(
            target=lambda: box.update(result=controller.run()), daemon=True
        )
        thread.start()

        # hammer serve for the whole episode; every surfaced error must
        # be TYPED (a RayTpuError subclass), never a bare crash
        serve_errors = []
        stop_serving = threading.Event()

        def client_loop():
            i = 1
            while not stop_serving.is_set():
                try:
                    out = ray_tpu.get(handle.remote(i), timeout=30)
                    assert out == f"ok-{i}"
                except Exception as exc:  # noqa: BLE001 - recorded for the typed-error assert
                    serve_errors.append(exc)
                i += 1
                time.sleep(0.05)

        client = threading.Thread(target=client_loop, daemon=True)
        client.start()

        deadline = time.monotonic() + 60
        while not controller.metrics_history and time.monotonic() < deadline:
            time.sleep(0.02)
        assert controller.metrics_history, "gang never started reporting"

        # the fleet exists because demand put it there
        assert scaler.stats["scale_ups"] >= 3  # 2 train + 1 serve node

        # ---- scheduled preemption of a gang-hosting spot node
        victim = next(
            n for n in rt.scheduler.nodes()
            if n.labels.get("node_type") == "spot-train"
            and rt.scheduler.resident_bundles(n.node_id.hex())
        )
        provider.preempt_after(victim, 0.01, warning_s=3.0)

        thread.join(timeout=120)
        stop_serving.set()
        client.join(timeout=30)
        assert not thread.is_alive(), "controller never finished"

        result = box["result"]
        assert result.status == RunStatus.FINISHED, result.error
        # the announced-preemption budget absorbed the episode; the
        # failure budget (0) stayed untouched
        assert result.num_preempt_restarts == 1
        assert provider.num_preemptions() == 1
        assert scaler.stats["replacements"] >= 1

        # the warning window outlives the (fast) drill run: wait for the
        # reclaim to actually land so the bundle contains `node.dead`
        deadline = time.monotonic() + 15
        while victim.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not victim.alive, "preempted node never died"

        untyped = [e for e in serve_errors if not isinstance(e, RayTpuError)]
        assert untyped == [], untyped
        # serve stayed (or got back) healthy
        status = serve.status()["fleet-echo"]
        assert status["live_replicas"] == 2, status

        # ---- one bundle tells the whole story, in causal order
        out = str(tmp_path / "fleet.tgz")
        state.postmortem(out, note="spot-fleet capstone")
        bundle = load_bundle(out)
        evs = bundle["events.jsonl"]
        vh = victim.node_id.hex()

        announced = _first(evs, "preempt.announced")
        assert announced["node"] == vh
        replace = _first(evs, "autoscaler.replace", replaces=vh)
        dead = _first(evs, "node.dead")
        assert dead["node"] == vh
        # replacement capacity was up BEFORE the preempted node died
        assert announced["ts"] <= replace["ts"] <= dead["ts"], \
            [announced, replace, dead]
        # the replacement demand is origin-tagged and gang-shaped
        assert replace["extra"]["origin"] == "replace"
        assert replace["extra"]["node_type"] == "spot-train"
        assert replace["extra"]["capacity_class"] == "spot"
        # the original fleet scale-ups carry their demand origins too
        origins = {
            (e.get("extra") or {}).get("origin")
            for e in evs if e.get("kind") == "autoscaler.scale_up"
        }
        assert "pg" in origins, origins      # the training gang's bundles
        assert "task" in origins, origins    # the serve replica actors

        # ---- goodput: the run's wall time fully bucketed, restart visible
        goodput = result.goodput
        assert goodput is not None and goodput["wall_time_s"] > 0
        total = sum(goodput["buckets"].values())
        assert abs(total - goodput["wall_time_s"]) \
            <= 0.05 * goodput["wall_time_s"]
        assert goodput["buckets"]["step_compute"] > 0
        assert goodput["buckets"]["ckpt_save"] > 0
        assert goodput["buckets"]["preempt_restart"] > 0

        # ---- the autoscaler gauges saw the episode
        text = registry().prometheus_text()
        assert "raytpu_autoscaler_managed_nodes" in text
        assert 'raytpu_autoscaler_scale_total{direction="up"}' in text
        for line in text.splitlines():
            if line.startswith("raytpu_autoscaler_preempt_replacements_total"):
                assert float(line.rsplit(" ", 1)[1]) >= 1.0
                break
        else:
            raise AssertionError("replacement counter missing:\n" + text)

        serve.shutdown()
    finally:
        if scaler is not None:
            scaler.stop()
        ray_tpu.shutdown()
