"""Kernel correctness: Pallas flash attention (interpret mode) vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (
    apply_rope,
    cross_entropy_loss,
    flash_attention,
    layernorm,
    mha_reference,
    rmsnorm,
    rope_frequencies,
)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("gqa", [False, True])
def test_flash_forward_matches_reference(causal, gqa):
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    b, hq, s, d = 2, 4, 256, 64
    hkv = 2 if gqa else hq
    q = _rand(kq, (b, hq, s, d))
    k = _rand(kk, (b, hkv, s, d))
    v = _rand(kv, (b, hkv, s, d))
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, implementation="pallas",
                          block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_forward_unpadded_vs_padded():
    # seq not a multiple of the block: wrapper pads + masks
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, s, d = 1, 2, 192, 64
    q = _rand(kq, (b, h, s, d))
    k = _rand(kk, (b, h, s, d))
    v = _rand(kv, (b, h, s, d))
    ref = mha_reference(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, implementation="pallas",
                          block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    key = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, s, d = 1, 2, 256, 64
    q = _rand(kq, (b, h, s, d))
    k = _rand(kk, (b, h, s, d))
    v = _rand(kv, (b, h, s, d))

    def loss_pallas(q, k, v):
        o = flash_attention(q, k, v, causal=causal, implementation="pallas")
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal)
        return jnp.sum(o * o)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-3, rtol=1e-3)


def test_flash_backward_gqa():
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    b, hq, hkv, s, d = 1, 4, 2, 128, 32
    q = _rand(kq, (b, hq, s, d))
    k = _rand(kk, (b, hkv, s, d))
    v = _rand(kv, (b, hkv, s, d))

    def loss_pallas(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, implementation="pallas") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-3, rtol=1e-3)


def test_rmsnorm_and_layernorm():
    x = _rand(jax.random.PRNGKey(4), (2, 8, 64))
    scale = jnp.ones((64,))
    bias = jnp.zeros((64,))
    out = rmsnorm(x, scale)
    expected = x / np.sqrt(np.mean(np.square(np.asarray(x)), -1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)
    ln = layernorm(x, scale, bias)
    np.testing.assert_allclose(np.asarray(ln).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ln).std(-1), 1.0, atol=1e-3)


def test_rope_rotation_preserves_norm():
    cos, sin = rope_frequencies(64, 128)
    x = _rand(jax.random.PRNGKey(5), (1, 2, 16, 64))
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(out[:, :, 0]), np.asarray(x[:, :, 0]), atol=1e-6)


def test_rope_with_positions():
    cos, sin = rope_frequencies(32, 64)
    x = _rand(jax.random.PRNGKey(6), (2, 1, 4, 32))
    pos = jnp.array([[3, 4, 5, 6], [0, 1, 2, 3]])
    out = apply_rope(x, cos, sin, positions=pos)
    # batch 1 with offset positions == default arange
    default = apply_rope(x[1:2], cos, sin)
    np.testing.assert_allclose(np.asarray(out[1:2]), np.asarray(default), atol=1e-6)


def test_cross_entropy_against_manual():
    logits = _rand(jax.random.PRNGKey(7), (4, 16))
    targets = jnp.array([1, 5, 2, 9])
    loss, n = cross_entropy_loss(logits, targets)
    logp = jax.nn.log_softmax(np.asarray(logits, dtype=np.float32), axis=-1)
    expected = -np.mean([logp[i, t] for i, t in enumerate(np.asarray(targets))])
    np.testing.assert_allclose(float(loss), expected, rtol=1e-6)
    assert float(n) == 4.0


def test_cross_entropy_masked():
    logits = _rand(jax.random.PRNGKey(8), (2, 4, 16))
    targets = jnp.zeros((2, 4), dtype=jnp.int32)
    mask = jnp.array([[1, 1, 0, 0], [1, 0, 0, 0]])
    loss, n = cross_entropy_loss(logits, targets, mask=mask)
    assert float(n) == 3.0
    assert np.isfinite(float(loss))


def test_cross_entropy_z_loss_increases_loss():
    logits = 5.0 * _rand(jax.random.PRNGKey(9), (4, 16))
    targets = jnp.array([0, 1, 2, 3])
    base, _ = cross_entropy_loss(logits, targets)
    with_z, _ = cross_entropy_loss(logits, targets, z_loss_coeff=1e-2)
    assert float(with_z) > float(base)


def test_fused_linear_cross_entropy_matches_dense():
    """The chunked fused head+CE (PERF_NOTES.md) must agree with the
    dense path — values AND gradients — including mask and z-loss."""
    from ray_tpu.ops.losses import fused_linear_cross_entropy

    key = jax.random.PRNGKey(11)
    b, s, e, v, chunk = 2, 8, 16, 32, 4
    x = _rand(key, (b, s, e))
    head = _rand(jax.random.PRNGKey(12), (e, v))
    targets = jax.random.randint(jax.random.PRNGKey(13), (b, s), 0, v)
    mask = jnp.array([[1] * 8, [1, 1, 1, 1, 0, 0, 0, 0]])

    def dense(x, head):
        logits = jnp.einsum("bse,ev->bsv", x, head)
        return cross_entropy_loss(
            logits, targets, mask=mask, z_loss_coeff=1e-3
        )[0]

    def fused(x, head):
        return fused_linear_cross_entropy(
            x, head, targets, chunk=chunk, mask=mask, z_loss_coeff=1e-3
        )[0]

    np.testing.assert_allclose(
        float(dense(x, head)), float(fused(x, head)), rtol=1e-5
    )
    gd = jax.grad(dense, argnums=(0, 1))(x, head)
    gf = jax.grad(fused, argnums=(0, 1))(x, head)
    for a, b_ in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)

    with pytest.raises(ValueError):
        fused_linear_cross_entropy(x, head, targets, chunk=5)
