"""Kernel correctness: Pallas flash attention (interpret mode) vs XLA reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (
    apply_rope,
    cross_entropy_loss,
    flash_attention,
    layernorm,
    mha_reference,
    rmsnorm,
    rope_frequencies,
)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("gqa", [False, True])
def test_flash_forward_matches_reference(causal, gqa):
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    b, hq, s, d = 2, 4, 256, 64
    hkv = 2 if gqa else hq
    q = _rand(kq, (b, hq, s, d))
    k = _rand(kk, (b, hkv, s, d))
    v = _rand(kv, (b, hkv, s, d))
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, implementation="pallas",
                          block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_forward_unpadded_vs_padded():
    # seq not a multiple of the block: wrapper pads + masks
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, s, d = 1, 2, 192, 64
    q = _rand(kq, (b, h, s, d))
    k = _rand(kk, (b, h, s, d))
    v = _rand(kv, (b, h, s, d))
    ref = mha_reference(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, implementation="pallas",
                          block_q=128, block_kv=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    key = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, s, d = 1, 2, 256, 64
    q = _rand(kq, (b, h, s, d))
    k = _rand(kk, (b, h, s, d))
    v = _rand(kv, (b, h, s, d))

    def loss_pallas(q, k, v):
        o = flash_attention(q, k, v, causal=causal, implementation="pallas")
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal)
        return jnp.sum(o * o)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-3, rtol=1e-3)


def test_flash_backward_gqa():
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    b, hq, hkv, s, d = 1, 4, 2, 128, 32
    q = _rand(kq, (b, hq, s, d))
    k = _rand(kk, (b, hkv, s, d))
    v = _rand(kv, (b, hkv, s, d))

    def loss_pallas(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, implementation="pallas") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-3, rtol=1e-3)


def test_rmsnorm_and_layernorm():
    x = _rand(jax.random.PRNGKey(4), (2, 8, 64))
    scale = jnp.ones((64,))
    bias = jnp.zeros((64,))
    out = rmsnorm(x, scale)
    expected = x / np.sqrt(np.mean(np.square(np.asarray(x)), -1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)
    ln = layernorm(x, scale, bias)
    np.testing.assert_allclose(np.asarray(ln).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ln).std(-1), 1.0, atol=1e-3)


def test_rope_rotation_preserves_norm():
    cos, sin = rope_frequencies(64, 128)
    x = _rand(jax.random.PRNGKey(5), (1, 2, 16, 64))
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(out[:, :, 0]), np.asarray(x[:, :, 0]), atol=1e-6)


def test_rope_with_positions():
    cos, sin = rope_frequencies(32, 64)
    x = _rand(jax.random.PRNGKey(6), (2, 1, 4, 32))
    pos = jnp.array([[3, 4, 5, 6], [0, 1, 2, 3]])
    out = apply_rope(x, cos, sin, positions=pos)
    # batch 1 with offset positions == default arange
    default = apply_rope(x[1:2], cos, sin)
    np.testing.assert_allclose(np.asarray(out[1:2]), np.asarray(default), atol=1e-6)


def test_cross_entropy_against_manual():
    logits = _rand(jax.random.PRNGKey(7), (4, 16))
    targets = jnp.array([1, 5, 2, 9])
    loss, n = cross_entropy_loss(logits, targets)
    logp = jax.nn.log_softmax(np.asarray(logits, dtype=np.float32), axis=-1)
    expected = -np.mean([logp[i, t] for i, t in enumerate(np.asarray(targets))])
    np.testing.assert_allclose(float(loss), expected, rtol=1e-6)
    assert float(n) == 4.0


def test_cross_entropy_masked():
    logits = _rand(jax.random.PRNGKey(8), (2, 4, 16))
    targets = jnp.zeros((2, 4), dtype=jnp.int32)
    mask = jnp.array([[1, 1, 0, 0], [1, 0, 0, 0]])
    loss, n = cross_entropy_loss(logits, targets, mask=mask)
    assert float(n) == 3.0
    assert np.isfinite(float(loss))


def test_cross_entropy_z_loss_increases_loss():
    logits = 5.0 * _rand(jax.random.PRNGKey(9), (4, 16))
    targets = jnp.array([0, 1, 2, 3])
    base, _ = cross_entropy_loss(logits, targets)
    with_z, _ = cross_entropy_loss(logits, targets, z_loss_coeff=1e-2)
    assert float(with_z) > float(base)


# ----------------------------------------------- pipelined kernel numerics
#
# The emit_pipeline kernel's interpret driver executes the same stage
# functions and slot arithmetic as the TPU driver, so these tests pin the
# pipelined dataflow (skewed stages, double-buffered score slots, causal
# trip counts) against the classic kernel BIT-FOR-BIT at f32 — the
# acceptance bar for swapping the default kernel.


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("gqa", [False, True])
def test_pipelined_forward_bitwise_vs_classic(causal, gqa):
    key = jax.random.PRNGKey(20)
    kq, kk, kv = jax.random.split(key, 3)
    b, hq, s, d = 2, 4, 256, 64
    hkv = 2 if gqa else hq
    q = _rand(kq, (b, hq, s, d))
    k = _rand(kk, (b, hkv, s, d))
    v = _rand(kv, (b, hkv, s, d))
    classic = flash_attention(q, k, v, causal=causal, implementation="pallas",
                              block_q=128, block_kv=64)
    pipe = flash_attention(q, k, v, causal=causal,
                           implementation="pallas_pipelined",
                           block_q=128, block_kv=64)
    np.testing.assert_array_equal(np.asarray(classic), np.asarray(pipe))
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(pipe), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pipelined_backward_bitwise_vs_classic(causal):
    key = jax.random.PRNGKey(21)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, s, d = 1, 2, 256, 64
    q = _rand(kq, (b, h, s, d))
    k = _rand(kk, (b, h, s, d))
    v = _rand(kv, (b, h, s, d))

    def loss(impl):
        def f(q, k, v):
            o = flash_attention(q, k, v, causal=causal, implementation=impl,
                                block_q=64, block_kv=64)
            return jnp.sum(o * o)
        return f

    gp = jax.grad(loss("pallas_pipelined"), argnums=(0, 1, 2))(q, k, v)
    gc = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gc):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-3, rtol=1e-3)


def test_pipelined_backward_gqa_matches_reference():
    key = jax.random.PRNGKey(22)
    kq, kk, kv = jax.random.split(key, 3)
    b, hq, hkv, s, d = 1, 4, 2, 128, 32
    q = _rand(kq, (b, hq, s, d))
    k = _rand(kk, (b, hkv, s, d))
    v = _rand(kv, (b, hkv, s, d))

    def loss_pipe(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, implementation="pallas_pipelined",
            block_q=64, block_kv=32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pipe, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-3, rtol=1e-3)


def test_pipelined_odd_sequence_tail():
    """Seq not a multiple of either block: wrapper pads, kernel masks; same
    tiles -> bitwise equal to the classic kernel, close to XLA."""
    key = jax.random.PRNGKey(23)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, s, d = 1, 2, 192, 64
    q = _rand(kq, (b, h, s, d))
    k = _rand(kk, (b, h, s, d))
    v = _rand(kv, (b, h, s, d))
    classic = flash_attention(q, k, v, implementation="pallas",
                              block_q=128, block_kv=64)
    pipe = flash_attention(q, k, v, implementation="pallas_pipelined",
                           block_q=128, block_kv=64)
    np.testing.assert_array_equal(np.asarray(classic), np.asarray(pipe))
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(pipe), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pipelined_lse_matches_classic_and_boundary():
    """flash_attention_with_lse parity incl. the fully-masked boundary
    (kv_len=0): both kernels share the finalize contract bit-for-bit."""
    from ray_tpu.ops.attention import (
        _fwd_pallas, _fwd_pipe, flash_attention_with_lse,
    )

    key = jax.random.PRNGKey(24)
    kq, kk, kv = jax.random.split(key, 3)
    q = _rand(kq, (1, 2, 256, 64))
    k = _rand(kk, (1, 2, 256, 64))
    v = _rand(kv, (1, 2, 256, 64))
    o1, l1 = flash_attention_with_lse(q, k, v, causal=True,
                                      implementation="pallas",
                                      block_q=128, block_kv=64)
    o2, l2 = flash_attention_with_lse(q, k, v, causal=True,
                                      implementation="pallas_pipelined",
                                      block_q=128, block_kv=64)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    # lse agrees with the dense logsumexp of the scaled causal scores
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q, np.float32),
                  np.asarray(k, np.float32)) / np.sqrt(64.0)
    mask = np.tril(np.ones((256, 256), bool))
    s = np.where(mask[None, None], s, -np.inf)
    dense_lse = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) + s.max(-1)
    np.testing.assert_allclose(np.asarray(l2)[..., 0], dense_lse,
                               atol=1e-4, rtol=1e-4)
    # boundary: kv_len=0 masks everything; pipelined == classic on the
    # degenerate rows too (shared finalize semantics)
    ob1, lb1 = _fwd_pallas(q, k, v, False, 0.125, 64, 64, 0, True)
    ob2, lb2 = _fwd_pipe(q, k, v, False, 0.125, 64, 64, 0, True)
    np.testing.assert_array_equal(np.asarray(ob1), np.asarray(ob2))
    np.testing.assert_array_equal(np.asarray(lb1), np.asarray(lb2))


def test_pipelined_auto_fallback_single_tile():
    """Shapes with <2 kv tiles fall back to the classic kernel instead of
    degenerate pipelining."""
    key = jax.random.PRNGKey(25)
    kq, kk, kv = jax.random.split(key, 3)
    q = _rand(kq, (1, 2, 64, 32))
    k = _rand(kk, (1, 2, 64, 32))
    v = _rand(kv, (1, 2, 64, 32))
    out = flash_attention(q, k, v, implementation="pallas_pipelined")
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_auto_loss_chunk_crossover():
    """Pins the dense->fused crossover at the measured v5e numbers: batch
    24 stays dense on a 16G chip, batch 32 (the measured regression) flips
    to the fused chunked path; unknown HBM (CPU) always dense."""
    from ray_tpu.ops.losses import auto_loss_chunk

    v5e = 16 * 1024**3
    assert auto_loss_chunk(24, 1024, 50257, v5e) == 0
    assert auto_loss_chunk(32, 1024, 50257, v5e) == 512
    # seq indivisible by the preferred chunks falls back down the ladder
    assert auto_loss_chunk(32, 1280, 50257, v5e) in (256, 128, 0)
    assert auto_loss_chunk(1024, 1024, 50257, None) == 0  # no HBM info
    assert auto_loss_chunk(24, 1024, 50257, 0) == 0


def test_check_kernel_fallbacks_wired():
    """scripts/check_kernel_fallbacks.py is now a shim over the raylint
    kernel-fallbacks rule; the repo-wide gate runs ONCE in
    tests/test_raylint.py. Here: the round-6 knobs stay registered and
    the shim's compat API resolves cfg reads."""
    import ast
    import importlib.util
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "check_kernel_fallbacks.py"
    spec = importlib.util.spec_from_file_location("ckf", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    config_tree = ast.parse(
        (repo / "ray_tpu" / "core" / "config.py").read_text()
    )
    flags = mod.defined_flags(config_tree)
    assert set(mod.REQUIRED_FLAGS) <= flags
    reads = mod.cfg_reads(ast.parse(
        "from .config import cfg\nx = cfg.attn_pipeline\n"
    ))
    assert reads == [(2, "attn_pipeline")]


def test_fused_linear_cross_entropy_matches_dense():
    """The chunked fused head+CE (PERF_NOTES.md) must agree with the
    dense path — values AND gradients — including mask and z-loss."""
    from ray_tpu.ops.losses import fused_linear_cross_entropy

    key = jax.random.PRNGKey(11)
    b, s, e, v, chunk = 2, 8, 16, 32, 4
    x = _rand(key, (b, s, e))
    head = _rand(jax.random.PRNGKey(12), (e, v))
    targets = jax.random.randint(jax.random.PRNGKey(13), (b, s), 0, v)
    mask = jnp.array([[1] * 8, [1, 1, 1, 1, 0, 0, 0, 0]])

    def dense(x, head):
        logits = jnp.einsum("bse,ev->bsv", x, head)
        return cross_entropy_loss(
            logits, targets, mask=mask, z_loss_coeff=1e-3
        )[0]

    def fused(x, head):
        return fused_linear_cross_entropy(
            x, head, targets, chunk=chunk, mask=mask, z_loss_coeff=1e-3
        )[0]

    np.testing.assert_allclose(
        float(dense(x, head)), float(fused(x, head)), rtol=1e-5
    )
    gd = jax.grad(dense, argnums=(0, 1))(x, head)
    gf = jax.grad(fused, argnums=(0, 1))(x, head)
    for a, b_ in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5)

    with pytest.raises(ValueError):
        fused_linear_cross_entropy(x, head, targets, chunk=5)
