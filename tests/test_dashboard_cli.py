"""Dashboard HTTP endpoints + CLI commands (reference: python/ray/
dashboard/, scripts/scripts.py)."""

import json
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard, stop_dashboard


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield
    stop_dashboard()
    ray_tpu.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_dashboard_serves_state_and_page():
    @ray_tpu.remote
    def work(x):
        return x + 1

    assert ray_tpu.get(work.remote(1)) == 2

    @ray_tpu.remote
    class A:
        def noop(self):
            return None

    a = A.options(name="dash-actor").remote()
    ray_tpu.get(a.noop.remote())

    url = start_dashboard(port=0)
    status, page = _get(url + "/")
    assert status == 200 and "ray_tpu dashboard" in page

    status, body = _get(url + "/api/summary")
    summary = json.loads(body)
    assert summary["nodes"] == 1
    assert summary["tasks_finished"] >= 1

    status, body = _get(url + "/api/actors")
    actors = json.loads(body)
    assert any(x["name"] == "dash-actor" for x in actors)

    status, body = _get(url + "/api/tasks")
    assert any(t["name"] == "work" for t in json.loads(body))

    status, body = _get(url + "/api/timeline")
    assert "traceEvents" in json.loads(body)

    status, body = _get(url + "/metrics")
    assert status == 200

    with pytest.raises(Exception):
        _get(url + "/api/nonsense")


def _run_cli(*args, check=True):
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args],
        capture_output=True, text=True, timeout=120,
    )
    if check:
        assert proc.returncode == 0, proc.stderr
    return proc


def test_cli_config_lists_flags():
    out = _run_cli("config").stdout
    assert "object_store_capacity_bytes" in out
    assert "RAY_TPU_NATIVE_STORE" in out


def test_cli_status():
    # autoscaler-style debug report: nodes + usage + telemetry sections
    out = _run_cli("--no-tpu", "status").stdout
    assert "Nodes: 1 (1 ALIVE)" in out
    assert "object store:" in out and "worker pool:" in out
    # --json keeps the machine-readable summary shape
    out = _run_cli("--no-tpu", "status", "--json").stdout
    assert '"nodes": 1' in out
    assert '"node_stats"' in out


def test_cli_job_submit_wait_and_logs():
    out = _run_cli(
        "job", "submit", "python -c 'print(\"hello-from-job\")'",
        "--job-id", "cli-test-job", "--wait",
    ).stdout
    assert "hello-from-job" in out
    assert "SUCCEEDED" in out

    failing = _run_cli(
        "job", "submit", "python -c 'raise SystemExit(3)'", "--wait",
        check=False,
    )
    assert failing.returncode == 1
    assert "FAILED" in failing.stdout


def test_rest_job_submission():
    """POST /api/jobs submits a real subprocess job (reference: dashboard
    job module behind `ray job submit`)."""
    from ray_tpu.jobs import default_job_manager

    url = start_dashboard(port=0)
    req = urllib.request.Request(
        url + "/api/jobs",
        data=json.dumps({
            "entrypoint": "python -c 'print(40+2)'",
            "job_id": "rest-job-1",
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.loads(r.read())["job_id"] == "rest-job-1"
    mgr = default_job_manager()
    assert mgr.wait("rest-job-1", timeout=60).value == "SUCCEEDED"
    assert "42" in mgr.logs("rest-job-1")
    # listed through the read API too
    status, body = _get(url + "/api/jobs")
    assert any(j["job_id"] == "rest-job-1" for j in json.loads(body))
    # bad payloads answer 400 without registering a phantom job
    bad = urllib.request.Request(
        url + "/api/jobs",
        data=json.dumps({"entrypoint": ["not", "a", "string"]}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        urllib.request.urlopen(bad, timeout=10)
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    assert all(j.entrypoint != ["not", "a", "string"] for j in mgr.list())
    # CSRF guard: form posts without a JSON content type are rejected
    form = urllib.request.Request(
        url + "/api/jobs",
        data=json.dumps({"entrypoint": "python -c 'print(1)'"}).encode(),
        headers={"Content-Type": "application/x-www-form-urlencoded"},
    )
    try:
        urllib.request.urlopen(form, timeout=10)
        raise AssertionError("expected HTTP 415")
    except urllib.error.HTTPError as e:
        assert e.code == 415


def test_rest_failure_paths():
    """Malformed bodies, wrong content types, unknown endpoints, and
    dead-job lookups all answer with errors instead of crashing the
    server or fabricating state."""
    from ray_tpu.jobs import default_job_manager

    url = start_dashboard(port=0)

    def post(data: bytes, ctype="application/json"):
        req = urllib.request.Request(
            url + "/api/jobs", data=data, headers={"Content-Type": ctype}
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    assert post(b"this is not json") == 400
    assert post(json.dumps({"no_entrypoint": True}).encode()) == 400
    assert post(json.dumps({"entrypoint": "echo hi"}).encode(),
                ctype="text/plain") == 415  # CSRF guard
    assert post(json.dumps({"entrypoint": ""}).encode()) == 400
    # none of the rejects registered a job
    assert all(
        j.job_id != "phantom" for j in default_job_manager().list()
    )

    def get_code(path):
        try:
            with urllib.request.urlopen(url + path, timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    # unknown API endpoint answers 500 with a JSON error, not a hang
    status, body = get_code("/api/nonsense")
    assert status == 500
    assert "unknown endpoint" in body

    # plain 404 for non-API paths
    status, _ = get_code("/definitely/not/here")
    assert status == 404


def test_dead_job_lookups():
    """status/logs/wait of a job id that never existed raise KeyError
    (CLI surfaces them; the REST read API simply omits the job)."""
    import pytest as _pytest

    from ray_tpu.jobs import default_job_manager

    mgr = default_job_manager()
    with _pytest.raises(KeyError):
        mgr.status("never-existed")
    with _pytest.raises(KeyError):
        mgr.logs("never-existed")
    with _pytest.raises(KeyError):
        mgr.wait("never-existed", timeout=1)
