"""Paged KV continuous batching (reference: vLLM paged attention +
chunked prefill behind vllm_engine.py:254; TPU recipe per PAPERS.md)."""

import time

import jax
import numpy as np
import pytest

from ray_tpu.models import forward, get_config, init_params
from ray_tpu.serve.llm.paged import PagedConfig, PageAllocator
from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine


def _greedy_reference(config, params, prompt, n):
    tokens = list(prompt)
    for _ in range(n):
        logits = forward(params, np.asarray([tokens], dtype=np.int32), config)
        tokens.append(int(np.argmax(np.asarray(logits[0, -1]))))
    return tokens[len(prompt):]


def _tiny_engine(model="llama-tiny", seed=0, **over):
    config = get_config(model)
    params = init_params(config, jax.random.PRNGKey(seed))
    defaults = dict(
        max_slots=4,
        paged=PagedConfig(
            page_size=8, num_pages=64, max_pages_per_slot=8, chunk_pages=2
        ),
    )
    defaults.update(over)
    return config, params, PagedLLMEngine(
        config, params, PagedEngineConfig(**defaults)
    )


# ------------------------------------------------------------------ allocator


def test_allocator_exhaustion_and_reuse():
    a = PageAllocator(num_pages=5)  # 4 allocatable (page 0 reserved)
    p = a.alloc(4)
    assert sorted(p) == [1, 2, 3, 4]
    assert a.alloc(1) is None
    a.free(p[:2])
    assert a.available == 2
    q = a.alloc(2)
    assert set(q) <= {1, 2, 3, 4}


# -------------------------------------------------------------- correctness


def test_paged_greedy_matches_full_forward():
    config, params, engine = _tiny_engine()
    try:
        prompt = [5, 17, 42, 7]
        got = engine.generate(prompt, max_tokens=8)
        expected = _greedy_reference(config, params, prompt, 8)
        assert got == expected, (got, expected)
    finally:
        engine.shutdown()


def test_paged_multi_chunk_prompt_matches():
    """A prompt spanning several prefill chunks (chunk = 16 tokens here)
    must produce the same continuation as the unpaged full forward."""
    config, params, engine = _tiny_engine()
    try:
        prompt = list(np.random.default_rng(3).integers(1, 200, size=41))
        got = engine.generate([int(t) for t in prompt], max_tokens=6)
        expected = _greedy_reference(config, params, prompt, 6)
        assert got == expected, (got, expected)
    finally:
        engine.shutdown()


def test_paged_continuous_batching_staggered():
    config, params, engine = _tiny_engine(model="gpt2-tiny", seed=1)
    try:
        prompts = [[1, 2, 3], [9, 8], [30, 31, 32, 33], [4], [100, 101]]
        streams = []
        for p in prompts:
            streams.append((p, engine.submit(p, max_tokens=6)))
            time.sleep(0.02)
        for p, s in streams:
            got = s.result(timeout=60)
            expected = _greedy_reference(engine.model_config, params, p, 6)
            assert got == expected, (p, got, expected)
    finally:
        engine.shutdown()


def test_long_prompt_does_not_block_running_stream():
    """Chunked prefill: while a long prompt ingests, an already-running
    stream must keep producing tokens (no head-of-line blocking)."""
    config, params, engine = _tiny_engine()
    try:
        fast = engine.submit([3, 1, 4], max_tokens=40)
        it = iter(fast)
        next(it)  # running
        # long prompt: 56 tokens = 4 chunks of prefill work
        long_prompt = [int(t) for t in
                       np.random.default_rng(0).integers(1, 200, size=56)]
        slow = engine.submit(long_prompt, max_tokens=4)
        fast_rest = [t for t in it]
        slow_out = slow.result(timeout=60)
        assert len(fast_rest) == 39
        assert slow_out == _greedy_reference(config, params, long_prompt, 4)
        # decode rounds ran interleaved with the 4+ prefill chunks
        assert engine.metrics["prefill_chunks"] >= 4
    finally:
        engine.shutdown()


def test_page_pool_backpressure_all_requests_complete():
    """More concurrent demand than pages: requests queue on the allocator
    and all finish correctly once pages recycle."""
    config, params, engine = _tiny_engine(
        max_slots=4,
        paged=PagedConfig(
            page_size=8, num_pages=9, max_pages_per_slot=4, chunk_pages=1
        ),
    )
    try:
        rng = np.random.default_rng(7)
        jobs = []
        for _ in range(6):
            p = [int(t) for t in rng.integers(1, 200, size=5)]
            jobs.append((p, engine.submit(p, max_tokens=10)))
        for p, s in jobs:
            got = s.result(timeout=120)
            expected = _greedy_reference(config, params, p, 10)
            assert got == expected, (p, got, expected)
        assert engine.allocator.available == 8  # all pages recycled
    finally:
        engine.shutdown()


def test_pages_scale_with_tokens_not_max_seq():
    """The paged pool must admit more concurrent sequences than a dense
    cache of the same byte budget: pages_in_use tracks actual tokens."""
    config, params, engine = _tiny_engine()
    try:
        s = engine.submit([1, 2, 3], max_tokens=4)
        s.result(timeout=60)
        # a 3+4 token sequence on page_size=8 peaks at exactly 1 page
        # (+chunk rounding), never the dense max_seq/page_size
        assert engine.metrics["pages_in_use"] <= 2
    finally:
        engine.shutdown()


def test_submit_validation():
    config, params, engine = _tiny_engine()
    try:
        with pytest.raises(ValueError, match="capacity"):
            engine.submit(list(range(60)), max_tokens=10)  # > 8 pages * 8
        with pytest.raises(ValueError, match="empty"):
            engine.submit([], max_tokens=1)
    finally:
        engine.shutdown()


def test_config_validation():
    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="multiple"):
        PagedLLMEngine(config, params, PagedEngineConfig(
            paged=PagedConfig(max_pages_per_slot=5, chunk_pages=2)))


def test_llm_server_paged_path():
    from ray_tpu.serve.llm.server import LLMServer

    server = LLMServer(
        "llama-tiny",
        engine_config=PagedEngineConfig(
            max_slots=2,
            paged=PagedConfig(
                page_size=8, num_pages=32, max_pages_per_slot=8, chunk_pages=2
            ),
        ),
    )
    try:
        out = server.generate({"prompt_tokens": [5, 6, 7], "max_tokens": 4})
        assert len(out["tokens"]) == 4
        assert out["usage"]["total_tokens"] == 7
        assert isinstance(server.engine, PagedLLMEngine)
        server.check_health()
    finally:
        server.engine.shutdown()


def test_engine_death_fails_streams_not_hangs():
    """A crash in the engine loop must surface on every pending stream
    instead of hanging consumers forever."""
    config, params, engine = _tiny_engine()
    try:
        def boom(*a, **k):
            raise RuntimeError("injected engine crash")

        engine._decode_block_plain = boom
        engine._decode_block_filtered = boom
        engine._batched_chunk = boom
        s = engine.submit([1, 2, 3], max_tokens=4)
        with pytest.raises(RuntimeError, match="injected engine crash"):
            s.result(timeout=30)
    finally:
        engine.shutdown()


def test_engine_death_in_decode_loop_fails_streams():
    """A crash AFTER prefill (in the decode block dispatch) must also
    surface on pending streams — the decode-path death boundary."""
    config, params, engine = _tiny_engine()
    try:
        def boom(*a, **k):
            raise RuntimeError("injected decode crash")

        engine._decode_block_plain = boom
        engine._decode_block_filtered = boom
        s = engine.submit([1, 2, 3], max_tokens=4)
        with pytest.raises(RuntimeError, match="injected decode crash"):
            s.result(timeout=30)
    finally:
        engine.shutdown()


def test_stalled_lane_token_survives_other_lanes_dispatch(monkeypatch):
    """Regression: a lane page-stalled mid-decode keeps its pending input
    token while other lanes keep dispatching blocks. Before the per-lane
    merge fix, _dispatch_decode_block replaced the whole on-device token
    vector with the block's final samples — garbage for excluded lanes
    (they attend over the scratch page) — so an unstalling lane resumed
    from a corrupt token and silently produced wrong output.

    Driven without the engine loop so the stall-vs-dispatch interleaving
    is deterministic: A's next block fits its pages, B needs a page the
    starved allocator cannot grant."""
    monkeypatch.setattr(PagedLLMEngine, "_loop", lambda self: None)
    config, params, engine = _tiny_engine(
        max_slots=2,
        decode_block_steps=2,
        paged=PagedConfig(
            page_size=4, num_pages=9, max_pages_per_slot=8, chunk_pages=2
        ),
    )
    try:
        engine.submit([5, 17, 42, 7, 3, 11], max_tokens=2)      # A: slot 0
        engine.submit([3, 11, 2, 29, 8, 1, 19, 4], max_tokens=4)  # B: slot 1
        engine._admit()
        assert not engine.slots[0].free and not engine.slots[1].free
        while any(s.prefilling for s in engine.slots):
            assert engine._prefill_tick()
        # both lanes now hold their first sampled token on device
        token_b_before = int(engine._tokens_dev[1])
        # starve the pool so B's mid-decode growth stalls
        n_free = engine.allocator.available
        if n_free:
            assert engine.allocator.alloc(n_free) is not None
        assert engine._dispatch_decode_block()
        assert engine.slots[1].stalled, "B should be page-stalled"
        assert not engine.slots[0].stalled, "A should have dispatched"
        assert engine.slots[0].position == 7
        assert int(engine._tokens_dev[1]) == token_b_before, (
            "stalled lane's pending token was clobbered by the dispatch"
        )
    finally:
        engine.shutdown()


def test_sampling_params_topk_topp_and_stop():
    config, params, engine = _tiny_engine()
    try:
        prompt = [5, 17, 42, 7]
        greedy = _greedy_reference(config, params, prompt, 6)
        # top_k=1 forces greedy even at high temperature
        got = engine.submit(
            prompt, max_tokens=6, temperature=5.0, top_k=1
        ).result(timeout=60)
        assert got == greedy, (got, greedy)
        # a vanishingly small nucleus keeps only the argmax token
        got = engine.submit(
            prompt, max_tokens=6, temperature=5.0, top_p=1e-6
        ).result(timeout=60)
        assert got == greedy, (got, greedy)
        # per-request stop token ends the stream early
        stop = greedy[2]
        got = engine.submit(
            prompt, max_tokens=6, stop_token_ids=[stop]
        ).result(timeout=60)
        assert got == greedy[:3], (got, greedy)
        with pytest.raises(ValueError, match="top_p"):
            engine.submit(prompt, max_tokens=2, top_p=0.0)
    finally:
        engine.shutdown()


def test_plain_decode_path_selected_for_greedy_batches():
    """Perf guard: all-greedy batches must take the sort-free plain block;
    a top-k/top-p lane switches the dispatch to the filtered block."""
    config, params, engine = _tiny_engine()
    try:
        counts = {"plain": 0, "filtered": 0}
        orig_plain = engine._decode_block_plain
        orig_filtered = engine._decode_block_filtered

        def plain(*a):
            counts["plain"] += 1
            return orig_plain(*a)

        def filtered(*a):
            counts["filtered"] += 1
            return orig_filtered(*a)

        engine._decode_block_plain = plain
        engine._decode_block_filtered = filtered

        engine.generate([1, 2, 3], max_tokens=6)  # greedy
        assert counts["plain"] >= 1 and counts["filtered"] == 0

        engine.submit([1, 2, 3], max_tokens=6, top_k=2,
                      temperature=1.0).result(timeout=60)
        assert counts["filtered"] >= 1
    finally:
        engine.shutdown()


# ------------------------------------------------------------- tensor parallel


def _tp_mesh(n):
    from ray_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(tp=n), devices=jax.devices()[:n])


def test_tp_engine_matches_single_device_greedy():
    """The TP-sharded engine (params Megatron-split, KV pool sharded on
    kv heads over the 8-device mesh) must emit EXACTLY the single-device
    greedy tokens — sharding is an execution detail, not a semantics
    change."""
    from ray_tpu.models.transformer import TransformerConfig

    config = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=8, n_kv_heads=8,
        d_ff=128, max_seq=512, pos_emb="rope", norm="rmsnorm", act="swiglu",
        use_bias=False, dtype=jax.numpy.float32,
    )
    params = init_params(config, jax.random.PRNGKey(0))
    ecfg = PagedEngineConfig(
        max_slots=4, decode_block_steps=4,
        paged=PagedConfig(page_size=16, num_pages=64, max_pages_per_slot=8,
                          chunk_pages=2),
    )
    prompt = list(range(1, 20))
    ref = PagedLLMEngine(config, params, ecfg)
    try:
        want = ref.generate(prompt, max_tokens=10, temperature=0.0)
    finally:
        ref.shutdown()

    tp = PagedLLMEngine(config, params, ecfg, mesh=_tp_mesh(8))
    try:
        got = tp.generate(prompt, max_tokens=10, temperature=0.0)
        # continuous batching still works under the mesh
        streams = [tp.submit(list(range(2, 12)), max_tokens=6) for _ in range(6)]
        outs = [s.result(timeout=120) for s in streams]
    finally:
        tp.shutdown()
    assert got == want, (got, want)
    assert all(len(o) == 6 for o in outs)
    assert all(o == outs[0] for o in outs)


def test_tp_engine_rejects_indivisible_heads():
    from ray_tpu.models.transformer import TransformerConfig

    config = TransformerConfig(
        vocab_size=64, d_model=48, n_layers=1, n_heads=6, n_kv_heads=3,
        d_ff=96, max_seq=128, pos_emb="rope", norm="rmsnorm", act="swiglu",
        use_bias=False, dtype=jax.numpy.float32,
    )
    params = init_params(config, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="must divide"):
        PagedLLMEngine(
            config, params,
            PagedEngineConfig(max_slots=2, paged=PagedConfig(
                page_size=8, num_pages=32, max_pages_per_slot=4, chunk_pages=2
            )),
            mesh=_tp_mesh(4),
        )
