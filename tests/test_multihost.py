"""Multi-host gang: N OS processes forming one jax.distributed SPMD job.

The flagship check is VERDICT round-1 item 3(b): a 2-process CPU
jax.distributed train run produces the SAME loss as the single-process
2-device run — the SPMD program is identical, only the process topology
changes (reference gang bootstrap: train/_internal/backend_executor.py:230).
"""

import os
import time

import jax
import pytest

from ray_tpu.train.multihost import MultihostWorkerGroup

# Each host process must come up on its own 1-device CPU backend, immune to
# the parent's 8-device XLA_FLAGS and the environment's TPU plugin.
_HOST_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def _make_env(n):
    return [dict(_HOST_ENV) for _ in range(n)]


def _tiny_train_fn(config):
    """Real ray_tpu train stack over whatever global mesh exists."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import get_config
    from ray_tpu.parallel import MeshSpec, build_mesh, default_rules
    from ray_tpu.train import (
        create_train_state,
        default_optimizer,
        make_train_step,
        report,
    )

    n_dev = config["n_devices"]
    devices = jax.devices()[:n_dev]
    mesh = build_mesh(MeshSpec(dp=n_dev), devices=devices)
    model_cfg = get_config("llama-tiny").replace(dtype=jnp.float32)
    opt = default_optimizer(1e-3, total_steps=10)
    state, shardings = create_train_state(
        model_cfg, opt, jax.random.PRNGKey(0), mesh, default_rules()
    )
    step = make_train_step(model_cfg, opt, mesh, state_shardings=shardings)

    # deterministic GLOBAL batch; each process feeds its own shard
    global_tokens = (
        np.arange(8 * 33, dtype=np.int32).reshape(8, 33) % model_cfg.vocab_size
    )
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec("dp", None))
    if jax.process_count() > 1:
        per = 8 // jax.process_count()
        local = global_tokens[jax.process_index() * per:(jax.process_index() + 1) * per]
        tokens = jax.make_array_from_process_local_data(sharding, local)
    else:
        tokens = jax.device_put(jnp.asarray(global_tokens), sharding)

    losses = []
    for _ in range(3):
        state, metrics = step(state, {"tokens": tokens})
        loss = float(metrics["loss"])
        losses.append(loss)
        try:
            report({"loss": loss})
        except RuntimeError:
            pass  # baseline invocation runs outside a session
    return losses


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="XLA rejects the 2-process gang on CPU: 'Multiprocess computations "
    "aren't implemented on the CPU backend' (pre-existing since seed)",
)
def test_two_process_distributed_matches_single_process():
    # baseline: same SPMD program on 2 devices of THIS process
    baseline = _tiny_train_fn({"n_devices": 2})

    group = MultihostWorkerGroup(
        num_workers=2, run_name="mh-test", env_per_worker=_make_env(2)
    )
    try:
        group.start()
        pids = group.pids()
        assert len(set(pids)) == 2 and os.getpid() not in pids
        futs = group.run_async(_tiny_train_fn, {"n_devices": 2})
        results = group.finish(futs, timeout=600)
    finally:
        group.shutdown()

    # every host computed the same global losses, equal to the baseline
    for host_losses in results:
        assert host_losses == pytest.approx(baseline, rel=1e-5)


def test_report_streaming_and_poll():
    def fn(config):
        from ray_tpu.train import report

        for i in range(3):
            report({"i": i})
        return "done"

    group = MultihostWorkerGroup(
        num_workers=1, run_name="mh-poll", env_per_worker=_make_env(1)
    )
    try:
        group.start()
        futs = group.run_async(fn, {})
        deadline = time.monotonic() + 60
        seen = 0
        while time.monotonic() < deadline:
            polls = group.poll([seen])
            seen += len(polls[0]["reports"])
            if polls[0]["done"]:
                break
            time.sleep(0.1)
        assert seen == 3
        assert group.finish(futs, timeout=10) == ["done"]
    finally:
        group.shutdown()


def test_host_crash_surfaces_in_poll():
    def fn(config):
        os._exit(9)

    group = MultihostWorkerGroup(
        num_workers=1, run_name="mh-crash", env_per_worker=_make_env(1)
    )
    try:
        group.start()
        group.run_async(fn, {})
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            polls = group.poll([0])
            if polls[0]["error"] or polls[0]["done"]:
                break
            time.sleep(0.1)
        assert polls[0]["error"] is not None
    finally:
        group.shutdown()
