"""Ownership: handle refcounting with auto-GC, and lineage reconstruction.

Reference behaviors modeled: ReferenceCounter local-handle counting
(reference_count.h:72 — objects free when the last reference dies),
ObjectRecoveryManager lineage re-execution (object_recovery_manager.h:43 —
get() of a lost object re-runs the task that created it).
"""

import gc

import numpy as np
import pytest

import ray_tpu as api


# ------------------------------------------------------------------- auto GC


def test_unreferenced_objects_are_gcd(runtime):
    store = runtime.object_store
    before = store.usage()["num_objects"]
    for i in range(20):
        ref = api.put(np.zeros(200_000, dtype=np.float64))  # 1.6 MB each
        del ref
    gc.collect()
    after = store.usage()["num_objects"]
    # puts have no lineage → entries drop entirely once the handle dies
    assert after <= before + 2, (before, after)
    assert store.stats["gc"] >= 19


def test_live_ref_is_not_gcd(runtime):
    store = runtime.object_store
    ref = api.put(np.arange(100_000))
    gc.collect()
    np.testing.assert_array_equal(api.get(ref), np.arange(100_000))
    assert store.stats["gc"] == 0


def test_task_result_dropped_before_completion(runtime):
    import time

    @api.remote
    def slow():
        time.sleep(0.3)
        return np.ones(100_000)

    store = runtime.object_store
    ref = slow.remote()
    oid = ref.object_id
    del ref
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        entry = store.entry(oid)
        if entry is None or entry.value is None and entry.event.is_set():
            break
        time.sleep(0.05)
    assert store.stats["gc"] >= 1  # sealed value released on arrival


def test_arg_refs_keep_objects_alive_through_actor_calls(runtime):
    @api.remote
    class Echo:
        def take(self, x):
            return int(np.sum(x))

    actor = Echo.remote()
    data = api.put(np.ones(1000, dtype=np.int64))
    ref = actor.take.remote(data)
    del data  # the in-flight call must pin the arg
    gc.collect()
    assert api.get(ref) == 1000


# ------------------------------------------------------- lineage reconstruction


def test_evicted_object_reconstructs_via_lineage():
    """Fill a tiny store with NO spill dir: LRU eviction marks READY objects
    LOST; a later get() re-executes the creating task instead of raising."""
    import ray_tpu

    rt = ray_tpu.init(
        num_cpus=4, object_store_capacity=1 << 20, detect_accelerators=False
    )
    try:
        calls = {"n": 0}

        @api.remote
        def make(i):
            calls["n"] += 1
            return np.full(60_000, i, dtype=np.float64)  # 480 KB

        refs = [make.remote(i) for i in range(6)]  # ~2.9 MB >> 1 MB capacity
        api.wait(refs, num_returns=len(refs), timeout=30)
        store = rt.object_store
        assert store.stats["evictions"] >= 1  # pressure really evicted
        # every object still readable — evicted ones come back via re-execution
        for i, ref in enumerate(refs):
            out = api.get(ref, timeout=30)
            assert out[0] == i and out.shape == (60_000,)
        assert store.stats["reconstructions"] >= 1
        assert calls["n"] > 6  # the task really re-ran
    finally:
        ray_tpu.shutdown()


def test_lost_object_without_lineage_raises():
    import ray_tpu
    from ray_tpu.core.exceptions import ObjectLostError
    from ray_tpu.core.object_store import ObjectState

    rt = ray_tpu.init(num_cpus=2, detect_accelerators=False)
    try:
        ref = api.put(np.ones(10))
        entry = rt.object_store.entry(ref.object_id)
        with entry.lock:  # simulate a loss with no owner_task recorded
            entry.state = ObjectState.LOST
            entry.value = None
        with pytest.raises(ObjectLostError):
            api.get(ref, timeout=5)
    finally:
        ray_tpu.shutdown()


def test_gcd_lineage_object_reconstructs_on_new_handle():
    """A task output whose handles all died is GC'd to LOST (lineage kept);
    a re-bound handle (e.g. unpickled) can still get() it back."""
    import pickle

    import ray_tpu

    rt = ray_tpu.init(num_cpus=2, detect_accelerators=False)
    try:
        @api.remote
        def produce():
            return np.arange(5000)

        ref = produce.remote()
        api.get(ref, timeout=10)
        blob = pickle.dumps(ref)
        oid = ref.object_id
        del ref
        gc.collect()
        entry = rt.object_store.entry(oid)
        assert entry is not None and entry.value is None  # GC'd, lineage kept
        ref2 = pickle.loads(blob)
        np.testing.assert_array_equal(api.get(ref2, timeout=30), np.arange(5000))
        assert rt.object_store.stats["reconstructions"] >= 1
    finally:
        ray_tpu.shutdown()
