"""Cross-process shared-memory channels (reference:
python/ray/experimental/channel/shared_memory_channel.py:151)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.experimental.channel import ChannelClosedError
from ray_tpu.experimental.shm_channel import ShmChannel


def test_roundtrip_and_versions():
    ch = ShmChannel(capacity=1 << 16, num_readers=1)
    try:
        ch.write({"a": 1})
        assert ch.read(0) == {"a": 1}
        ch.write([1, 2, 3])
        assert ch.read(0) == [1, 2, 3]
    finally:
        ch.close()
        ch.unlink()


def test_backpressure_blocks_writer():
    ch = ShmChannel(capacity=1 << 16, num_readers=1)
    try:
        ch.write("v1")
        with pytest.raises(TimeoutError):
            ch.write("v2", timeout=0.2)  # v1 unconsumed
        assert ch.read(0) == "v1"
        ch.write("v2", timeout=5)
        assert ch.read(0) == "v2"
    finally:
        ch.close()
        ch.unlink()


def test_two_readers_each_see_every_version():
    ch = ShmChannel(capacity=1 << 16, num_readers=2)
    try:
        ch.write("x")
        assert ch.read(0) == "x"
        with pytest.raises(TimeoutError):
            ch.write("y", timeout=0.2)  # reader 1 lagging
        assert ch.read(1) == "x"
        ch.write("y", timeout=5)
        assert ch.read(0) == "y" and ch.read(1) == "y"
    finally:
        ch.close()
        ch.unlink()


def test_closed_channel_raises():
    ch = ShmChannel(capacity=1 << 12)
    try:
        ch.write(1)
        assert ch.read(0) == 1
        ch.close()
        with pytest.raises(ChannelClosedError):
            ch.read(0, timeout=1)
        with pytest.raises(ChannelClosedError):
            ch.write(2, timeout=1)
    finally:
        ch.unlink()


def test_capacity_guard():
    ch = ShmChannel(capacity=128)
    try:
        with pytest.raises(ValueError, match="exceeds channel capacity"):
            ch.write(np.zeros(1024))
    finally:
        ch.close()
        ch.unlink()


def test_cross_process_actor_pipeline(runtime):
    """The real point: a channel endpoint rides into a PROCESS actor and
    values stream driver -> actor -> driver through shared memory, in
    order, with backpressure."""

    @ray_tpu.remote(executor="process")
    class Stage:
        def __init__(self, inbound, outbound):
            self.inbound = inbound      # ShmChannelReader (unpickled in child)
            self.outbound = outbound    # ShmChannel (writer end)

        def pump(self, n):
            for _ in range(n):
                arr = self.inbound.read(timeout=30)
                self.outbound.write(arr * 2)
            return "done"

    inbound = ShmChannel(capacity=1 << 20, num_readers=1)
    outbound = ShmChannel(capacity=1 << 20, num_readers=1)
    try:
        stage = Stage.remote(inbound.reader(0), outbound)
        result = stage.pump.remote(5)
        for i in range(5):
            inbound.write(np.full(1000, i, dtype=np.int64))
            out = outbound.read(0, timeout=30)
            assert out[0] == i * 2 and out.shape == (1000,)
        assert ray_tpu.get(result, timeout=60) == "done"
    finally:
        inbound.close()
        outbound.close()
        inbound.unlink()
        outbound.unlink()


def test_stale_channel_files_reaped(tmp_path, monkeypatch):
    """Channel files with no live ENDPOINT (nobody holds the shared
    flock lease) are swept at the next channel creation; files any open
    endpoint still leases survive — even if their creator died (dag
    pipelines outlive the driver that made their channels)."""
    import os

    from ray_tpu.experimental import shm_channel as sc

    monkeypatch.setattr(sc, "_shm_dir", lambda: str(tmp_path))
    monkeypatch.setattr(sc, "_reaped_once", False)
    abandoned = tmp_path / "ray_tpu_chan_999999999_x"  # no lease holder
    abandoned.write_bytes(b"\x00" * 64)
    # a LIVE channel: its endpoint object holds the flock lease
    live = sc.ShmChannel(capacity=1024, num_readers=1)
    monkeypatch.setattr(sc, "_reaped_once", False)  # sweep again

    chan = sc.ShmChannel(capacity=1024, num_readers=1)
    try:
        assert not abandoned.exists(), "abandoned file survived the sweep"
        assert os.path.exists(live.path), "leased channel was reaped"
        # the lease, not the creator pid, is the liveness signal:
        # re-sweeping with both endpoints open leaves both alone
        monkeypatch.setattr(sc, "_reaped_once", False)
        sc._reap_stale_channels(str(tmp_path))
        assert os.path.exists(live.path) and os.path.exists(chan.path)
    finally:
        for ch in (chan, live):
            ch.close()
            ch.unlink()
        assert not os.path.exists(live.path)
