"""Test harness: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; sharding/collective code is
validated on a virtual CPU mesh (the standard JAX testing pattern), mirroring
how the reference tests multi-node behavior with N raylets on one machine
(/root/reference/python/ray/cluster_utils.py:135).
"""

import os

# The axon sitecustomize imports jax at interpreter startup (before pytest
# loads this file), so plain env vars are too late for JAX_PLATFORMS. The
# backends themselves initialize lazily, so config.update still lands as
# long as it runs before the first jax.devices() call — which this does.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def runtime():
    """A fresh single-node runtime per test."""
    import ray_tpu

    rt = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def cluster4():
    """A 4-logical-node cluster (multi-node-on-one-host test pattern)."""
    import ray_tpu

    rt = ray_tpu.init(num_cpus=4, num_nodes=4, detect_accelerators=False)
    yield rt
    ray_tpu.shutdown()
