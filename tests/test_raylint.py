"""raylint: the unified static-analysis framework (scripts/raylint).

Covers the engine (suppression comments, baseline round-trip, reporters),
positive/negative fixtures for each NEW rule (lock-discipline,
lock-order, blocking-under-lock, jax-hot-path), the legacy rules through
the registry, and the tier-1 gate: ONE full-rule-set run over ray_tpu/
replacing the five separate check-script invocations, with per-rule
finding counts in the failure message.
"""

import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from scripts.raylint import REGISTRY, Project, run  # noqa: E402
from scripts.raylint.baseline import Baseline  # noqa: E402
from scripts.raylint.reporters import render_json, render_text  # noqa: E402

ALL_RULES = {
    "typed-errors", "metrics-names", "atomic-writes", "lazy-jax",
    "kernel-fallbacks", "lock-discipline", "lock-order",
    "blocking-under-lock", "jax-hot-path", "event-kinds",
    "request-phase", "step-phase", "gcs-durable-mutations",
}


def _project(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(tmp_path)


def test_registry_has_all_rules():
    assert set(REGISTRY) == ALL_RULES
    for rule in REGISTRY.values():
        assert rule.doc, f"{rule.name} has no doc"


# ------------------------------------------------------------ lock-discipline


def test_lock_discipline_flags_unlocked_access(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        import threading

        class Table:
            def __init__(self):
                self._rows = {}  # guarded-by: _lock
                self._lock = threading.Lock()

            def get(self, k):
                with self._lock:
                    return self._rows.get(k)

            def racy(self, k):
                return self._rows.get(k)
    """})
    result = run(proj, rules=["lock-discipline"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert f.rule == "lock-discipline"
    assert "Table._rows" in f.message and "guarded-by" in f.message
    assert proj.file("ray_tpu/core/m.py").lines[f.line - 1].strip() == \
        "return self._rows.get(k)"


def test_lock_discipline_honors_holds_lock_and_init(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        import threading

        class Table:
            def __init__(self):
                self._rows = {}  # guarded-by: _lock
                self._lock = threading.Lock()
                self._rows["seed"] = 1  # __init__ precedes sharing

            def _purge_locked(self):  # holds-lock: _lock
                self._rows.clear()

            def purge(self):
                with self._lock:
                    self._purge_locked()
    """})
    assert run(proj, rules=["lock-discipline"]).findings == []


def test_lock_discipline_guard_alias_condition(tmp_path):
    # a Condition and the Lock it wraps are one guard under two names
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        import threading

        class Pool:
            def __init__(self):
                self._idle = []  # guarded-by: _lock|_free
                self._lock = threading.Lock()
                self._free = threading.Condition(self._lock)

            def acquire(self):
                with self._free:
                    return self._idle.pop()

            def count(self):
                with self._lock:
                    return len(self._idle)
    """})
    assert run(proj, rules=["lock-discipline"]).findings == []


# ----------------------------------------------------------------- lock-order


def test_lock_order_cycle_detected(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        class S:
            def ab(self):
                with self._node_lock:
                    with self._table_lock:
                        pass

            def ba(self):
                with self._table_lock:
                    with self._node_lock:
                        pass
    """})
    result = run(proj, rules=["lock-order"])
    assert len(result.findings) == 1
    assert "cycle" in result.findings[0].message
    assert "S._node_lock" in result.findings[0].message


def test_lock_order_dag_is_clean(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        class S:
            def ab(self):
                with self._node_lock:
                    with self._table_lock:
                        pass

            def also_ab(self):
                with self._node_lock:
                    with self._table_lock:
                        pass
    """})
    assert run(proj, rules=["lock-order"]).findings == []


def test_lock_order_same_name_in_other_class_not_aliased(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        class A:
            def f(self):
                with self._x_lock:
                    with self._y_lock:
                        pass

        class B:
            def g(self):
                with self._y_lock:
                    with self._x_lock:
                        pass
    """})
    # A._x_lock and B._x_lock are different objects: no cycle
    assert run(proj, rules=["lock-order"]).findings == []


# -------------------------------------------------------- blocking-under-lock


def test_blocking_under_lock_positive(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        import time

        class Beat:
            def tick(self):
                with self._lock:
                    time.sleep(0.1)
                    self._client.call("heartbeat")
                    self._thread.join()
                    self._fut.result()

            def ok(self):
                with self._lock:
                    parts = ",".join(["a", "b"])  # str.join: not blocking
                time.sleep(0.1)  # outside the lock: fine
                return parts
    """})
    result = run(proj, rules=["blocking-under-lock"])
    msgs = [f.message for f in result.findings]
    assert len(msgs) == 4
    assert any("time.sleep" in m for m in msgs)
    assert any("synchronous RPC" in m for m in msgs)
    assert any(".join()" in m for m in msgs)
    assert any(".result()" in m for m in msgs)


def test_blocking_under_lock_nested_with_and_closures(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        import time

        class C:
            def nested(self):
                with self._a_lock:
                    with self._b_lock:
                        time.sleep(1)

            def closure_runs_later(self):
                with self._lock:
                    cb = lambda: time.sleep(1)
                return cb
    """})
    result = run(proj, rules=["blocking-under-lock"])
    assert len(result.findings) == 1
    assert "_a_lock, _b_lock" in result.findings[0].message


def test_blocking_under_lock_io_and_serialization(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/m.py": """
        import cloudpickle

        class Snap:
            def save(self, path):
                with self._lock:
                    blob = cloudpickle.dumps(self._data)
                    with open(path, "wb") as f:
                        pass
    """})
    result = run(proj, rules=["blocking-under-lock"])
    assert len(result.findings) == 2
    assert any("cloudpickle.dumps" in f.message for f in result.findings)
    assert any("open()" in f.message for f in result.findings)


# --------------------------------------------------------------- jax-hot-path


def test_jax_hot_path_reachable_host_sync(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/train/m.py": """
        import jax

        def helper(x):
            return x.item()

        @jax.jit
        def step(state, batch):
            return helper(state) + batch

        def cold(x):
            return x.item()  # NOT reachable from a jit root
    """})
    result = run(proj, rules=["jax-hot-path"])
    assert len(result.findings) == 1
    assert ".item()" in result.findings[0].message
    assert "helper()" in result.findings[0].message


def test_jax_hot_path_cross_module_reachability(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/train/step.py": """
            import jax
            from ..ops.loss import loss_fn

            @jax.jit
            def step(state):
                return loss_fn(state)
        """,
        "ray_tpu/ops/loss.py": """
            def loss_fn(x):
                print(x)  # host sync in a helper the jitted step calls
                return x
        """,
    })
    result = run(proj, rules=["jax-hot-path"])
    assert len(result.findings) == 1
    assert result.findings[0].path == "ray_tpu/ops/loss.py"
    assert "print" in result.findings[0].message


def test_jax_hot_path_step_loop_and_shape_exemption(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/train/m.py": """
        def train(step_fn, state, batches):
            for batch in batches:
                state, metrics = step_fn(state, batch)
                tokens = float(batch.shape[0] * batch.shape[1])  # static
                loss = float(metrics["loss"])  # device sync per iteration
            return loss
    """})
    result = run(proj, rules=["jax-hot-path"])
    assert len(result.findings) == 1
    assert "step-dispatch loop" in result.findings[0].message
    assert result.findings[0].line == 6


def test_jax_hot_path_recompile_traps(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/ops/m.py": """
        import jax

        def rebuild_per_iter(fs, x):
            for f in fs:
                g = jax.jit(f)  # fresh wrapper per iteration
                x = g(x)
            return x

        def lam(x):
            return jax.jit(lambda y: y + 1)(x)  # fresh lambda per call

        module_level = jax.jit(lambda y: y)  # built once: fine
    """})
    result = run(proj, rules=["jax-hot-path"])
    msgs = [f.message for f in result.findings]
    assert any("inside a loop" in m for m in msgs)
    assert any("jit(lambda" in m for m in msgs)
    assert len(msgs) == 2


# ------------------------------------------------------ suppression + baseline


def test_line_and_file_suppressions(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/core/a.py": """
            import time

            class C:
                def f(self):
                    with self._lock:
                        time.sleep(1)  # raylint: disable=blocking-under-lock
        """,
        "ray_tpu/core/b.py": """
            # raylint: disable-file=blocking-under-lock
            import time

            class C:
                def f(self):
                    with self._lock:
                        time.sleep(1)
                def g(self):
                    with self._lock:
                        time.sleep(2)
        """,
    })
    result = run(proj, rules=["blocking-under-lock"])
    assert result.findings == []
    assert result.suppressed == 3


def test_suppression_is_rule_scoped(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/a.py": """
        import time

        class C:
            def f(self):
                with self._lock:
                    time.sleep(1)  # raylint: disable=jax-hot-path
    """})
    result = run(proj, rules=["blocking-under-lock"])
    assert len(result.findings) == 1  # wrong rule name: not suppressed


def test_baseline_roundtrip_add_and_remove(tmp_path):
    files = {"ray_tpu/core/a.py": """
        import time

        class C:
            def f(self):
                with self._lock:
                    time.sleep(1)
    """}
    proj = _project(tmp_path, files)
    bl_path = tmp_path / "baseline.json"

    # 1. finding exists without a baseline
    result = run(proj, rules=["blocking-under-lock"])
    assert len(result.findings) == 1

    # 2. write the baseline -> rerun is clean, finding counted as baselined
    Baseline.empty().write(bl_path, result.findings, proj)
    baseline = Baseline.load(bl_path)
    result2 = run(proj, rules=["blocking-under-lock"], baseline=baseline)
    assert result2.findings == [] and len(result2.baselined) == 1
    entry = json.loads(bl_path.read_text())["entries"][0]
    assert entry["rule"] == "blocking-under-lock"
    assert "justification" in entry

    # 3. the baseline is line-number insensitive: shifting the file down
    # keeps matching the same finding
    src = (tmp_path / "ray_tpu/core/a.py").read_text()
    (tmp_path / "ray_tpu/core/a.py").write_text("# moved\n" + src)
    proj3 = Project(tmp_path)
    result3 = run(proj3, rules=["blocking-under-lock"], baseline=baseline)
    assert result3.findings == [] and len(result3.baselined) == 1

    # 4. fixing the violation leaves a STALE baseline entry (not an error)
    (tmp_path / "ray_tpu/core/a.py").write_text(
        textwrap.dedent("""
            class C:
                def f(self):
                    with self._lock:
                        pass
        """)
    )
    proj4 = Project(tmp_path)
    result4 = run(proj4, rules=["blocking-under-lock"], baseline=baseline)
    assert result4.findings == [] and result4.baselined == []
    assert len(result4.stale_baseline) == 1

    # 5. --write-baseline semantics: rewriting drops the stale entry
    baseline.write(bl_path, result4.findings, proj4)
    assert json.loads(bl_path.read_text())["entries"] == []


def test_baseline_preserves_justifications(tmp_path):
    files = {"ray_tpu/core/a.py": """
        import time

        class C:
            def f(self):
                with self._lock:
                    time.sleep(1)
    """}
    proj = _project(tmp_path, files)
    bl_path = tmp_path / "baseline.json"
    result = run(proj, rules=["blocking-under-lock"])
    Baseline.empty().write(bl_path, result.findings, proj)
    data = json.loads(bl_path.read_text())
    data["entries"][0]["justification"] = "sleep is load-bearing here"
    bl_path.write_text(json.dumps(data))
    # regenerate: the human justification must survive
    Baseline.load(bl_path).write(bl_path, result.findings, proj)
    entry = json.loads(bl_path.read_text())["entries"][0]
    assert entry["justification"] == "sleep is load-bearing here"


# ------------------------------------------------------------------ reporters


def test_json_reporter_schema(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/a.py": """
        import time

        class C:
            def f(self):
                with self._lock:
                    time.sleep(1)
    """})
    result = run(proj, rules=["blocking-under-lock", "lock-order"])
    payload = render_json(result)
    assert payload["version"] == 1
    assert payload["ok"] is False
    assert set(payload["counts"]) == {"blocking-under-lock", "lock-order"}
    assert payload["counts"]["blocking-under-lock"] == 1
    assert payload["counts"]["lock-order"] == 0  # zero counts included
    (finding,) = payload["findings"]
    assert set(finding) == {"rule", "path", "line", "message"}
    assert finding["path"] == "ray_tpu/core/a.py"
    text = render_text(result)
    assert "ray_tpu/core/a.py" in text and "[blocking-under-lock]" in text
    assert "blocking-under-lock=1" in text


# -------------------------------------------------- legacy rules via registry


def test_legacy_rules_fire_through_registry(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/__init__.py": "",
        "ray_tpu/core/exceptions.py": """
            class UnexportedError(Exception):
                pass
        """,
        "ray_tpu/serve/oops.py": """
            try:
                x = 1
            except:
                pass
        """,
        "ray_tpu/train/ckpt.py": """
            import json

            def save(path, obj):
                with open(path, "w") as f:
                    json.dump(obj, f)
        """,
        "ray_tpu/core/m.py": """
            c = Counter("unprefixed_total", "x")
        """,
        "ray_tpu/ops/kern.py": """
            from jax.experimental.pallas import tpu as pltpu

            def kernel(ref):
                pltpu.emit_pipeline
        """,
    })
    result = run(proj, rules=[
        "typed-errors", "metrics-names", "atomic-writes", "kernel-fallbacks",
    ])
    by_rule = {}
    for f in result.findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("bare 'except:'" in f.message
               for f in by_rule["typed-errors"])
    assert any("UnexportedError" in f.message
               for f in by_rule["typed-errors"])
    assert any("raytpu_ prefix" in f.message
               for f in by_rule["metrics-names"])
    assert any("non-atomic state write" in f.message
               for f in by_rule["atomic-writes"])
    assert any("pltpu import is not guarded" in f.message
               for f in by_rule["kernel-fallbacks"])
    assert any("no registered non-TPU fallback" in f.message
               for f in by_rule["kernel-fallbacks"])


def test_lazy_jax_rule_through_registry(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/util/profiling.py": "import jax\n",
        "ray_tpu/core/stats.py": "def f():\n    import jax\n",
        "ray_tpu/util/tracing.py": "x = 1\n",
    })
    result = run(proj, rules=["lazy-jax"])
    assert len(result.findings) == 1
    assert result.findings[0].path == "ray_tpu/util/profiling.py"
    assert "module-level jax import" in result.findings[0].message


# ----------------------------------------------------------------- step-phase


_STEPLOG_FIXTURE = """
    STEP_PHASES = {
        "data_wait": "input wait",
        "fwd_bwd_compute": "device compute",
        "other": "seal",
    }

    def register_step_phase(phase, doc=""):
        STEP_PHASES.setdefault(phase, doc)

    def mark(phase, dur_s, **kw):
        pass
"""


def test_step_phase_flags_unregistered_and_dynamic(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/train/steplog.py": _STEPLOG_FIXTURE,
        "ray_tpu/train/loop.py": """
            from . import steplog

            def f(run, dur, name):
                steplog.mark("data_wait", dur, run=run, rank=0, step=1)
                steplog.mark("fwd_bwd", dur, run=run, rank=0, step=1)
                steplog.mark(name, dur, run=run, rank=0, step=1)
        """,
    })
    result = run(proj, rules=["step-phase"])
    msgs = [f.message for f in result.findings]
    assert len(msgs) == 2, msgs
    assert any("'fwd_bwd' is not registered" in m for m in msgs)
    assert any("string literal" in m for m in msgs)


def test_step_phase_honors_registry_and_aliases(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/train/steplog.py": _STEPLOG_FIXTURE,
        "ray_tpu/train/custom.py": """
            from .steplog import mark, register_step_phase

            register_step_phase("grad_clip", "custom backend phase")

            def f(dur):
                mark("grad_clip", dur, run="r", rank=0, step=1)
                mark("other", dur, run="r", rank=0, step=1, wall_s=dur)
        """,
        "ray_tpu/train/singleton.py": """
            from . import steplog

            def g(dur):
                steplog.log().mark("data_wait", dur, run="r", rank=0, step=1)
        """,
    })
    assert run(proj, rules=["step-phase"]).findings == []


def test_step_phase_exempts_steplog_module_and_other_marks(tmp_path):
    proj = _project(tmp_path, {
        # steplog.py itself forwards dynamic phases: exempt
        "ray_tpu/train/steplog.py": _STEPLOG_FIXTURE + """
    def remark(phase, dur_s):
        mark(phase, dur_s)
        """,
        # an unrelated .mark receiver makes no step-phase claim
        "ray_tpu/train/spans.py": """
            def f(tracer, dur):
                tracer.mark(dur)
        """,
    })
    assert run(proj, rules=["step-phase"]).findings == []


def test_step_phase_production_call_sites_are_typed():
    """Production evidence: the REAL tree passes the rule, the trainer's
    decomposition marks every registered phase, and the schema the rule
    keys on exists."""
    from ray_tpu.train.steplog import STEP_PHASES

    trainer_src = (REPO / "ray_tpu" / "train" / "trainer.py").read_text()
    for phase in STEP_PHASES:
        assert f'steplog.mark("{phase}"' in trainer_src, phase
    result = run(Project(REPO), rules=["step-phase"])
    assert result.findings == [], [f.location for f in result.findings]


# ---------------------------------------------------------- gcs-durable-mutations


_GCS_FIXTURE_HEADER = """
    WAL_EXEMPT_FUNCTIONS = ("__init__", "restore", "_apply", "replay_wal")

    class KVStore:
        def __init__(self):
            self._data = {}
            self._journal = None
"""


def test_gcs_durable_mutations_flags_unjournaled_writer(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/gcs.py": _GCS_FIXTURE_HEADER + """
        def put(self, key, value, namespace="default"):
            self._data[(namespace, key)] = value

        def delete(self, key, namespace="default"):
            return self._data.pop((namespace, key), None)
    """})
    result = run(proj, rules=["gcs-durable-mutations"])
    assert {f.line for f in result.findings}, result.findings
    assert all("_journal" in f.message for f in result.findings)
    assert len(result.findings) == 2  # put and delete both unjournaled


def test_gcs_durable_mutations_journaled_and_exempt_pass(tmp_path):
    proj = _project(tmp_path, {"ray_tpu/core/gcs.py": _GCS_FIXTURE_HEADER + """
        def put(self, key, value, namespace="default"):
            self._data[(namespace, key)] = value
            if self._journal is not None:
                self._journal("kv_put", (key, value, namespace))

        def restore(self, payload):
            for k, v in payload:
                self._data[k] = v  # replay: exempt by name
    """})
    result = run(proj, rules=["gcs-durable-mutations"])
    assert result.findings == [], [f.message for f in result.findings]


def test_gcs_durable_mutations_flags_external_table_reach(tmp_path):
    proj = _project(tmp_path, {
        "ray_tpu/core/gcs.py": _GCS_FIXTURE_HEADER,
        "ray_tpu/core/other.py": """
            def sneak(runtime, key, value):
                runtime.gcs.kv._data[("default", key)] = value

            def scrub(gcs, name):
                gcs._named_actors.pop(("default", name), None)

            def fine(runtime, key, value):
                runtime.gcs.kv.put(key, value)

            def unrelated(cache, key):
                cache._data[key] = 1  # not a kv/gcs receiver: no claim
        """,
    })
    result = run(proj, rules=["gcs-durable-mutations"])
    locs = sorted(f.line for f in result.findings)
    assert len(result.findings) == 2, [f.message for f in result.findings]
    assert all("bypasses" in f.message for f in result.findings)
    assert locs == [3, 6]


def test_gcs_durable_mutations_production_write_path_is_journaled():
    """Production evidence: the REAL core/gcs.py passes the rule — every
    durable-table mutator journals or is WAL-exempt — and the journal
    hook + exemption tuple the rule keys on actually exist."""
    gcs_src = (REPO / "ray_tpu" / "core" / "gcs.py").read_text()
    assert "WAL_EXEMPT_FUNCTIONS" in gcs_src
    assert "_journal" in gcs_src
    result = run(Project(REPO), rules=["gcs-durable-mutations"])
    assert result.findings == [], [f.location for f in result.findings]


# ------------------------------------------------------------------ tier-1 gate


def test_raylint_tier1_gate_full_repo():
    """THE tier-1 static-analysis gate: one full-rule-set run over
    ray_tpu/ (replacing the five separate check-script subprocesses),
    under a time budget, failing with per-rule counts + file:line."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.raylint", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    elapsed = time.monotonic() - t0
    assert proc.stdout, proc.stderr
    payload = json.loads(proc.stdout)
    counts = payload["counts"]
    detail = "; ".join(
        f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}"
        for f in payload["findings"]
    )
    assert proc.returncode == 0 and payload["ok"], (
        f"raylint gate failed — per-rule counts {counts} — {detail}"
    )
    # the single run covers the full registry (zeros reported too)
    assert set(counts) == ALL_RULES
    assert elapsed < 20, f"raylint run took {elapsed:.1f}s (budget: 20s)"
    # every baselined finding carries a real justification
    baseline = json.loads(
        (REPO / "scripts" / "raylint" / "baseline.json").read_text()
    )
    for entry in baseline["entries"]:
        assert entry["justification"], entry
        assert "TODO" not in entry["justification"], (
            f"baseline entry without justification: {entry}"
        )


def test_raylint_rules_each_have_production_evidence():
    """Each NEW analysis pass demonstrably fires on production code:
    either a fix landed this PR (regression-pinned here) or a baselined
    finding with justification exists."""
    baseline = json.loads(
        (REPO / "scripts" / "raylint" / "baseline.json").read_text()
    )
    baselined_rules = {e["rule"] for e in baseline["entries"]}
    # blocking-under-lock + jax-hot-path: baselined production findings
    assert "blocking-under-lock" in baselined_rules
    assert "jax-hot-path" in baselined_rules
    # lock-discipline: its production findings were FIXED this PR; pin
    # the fixes so they do not regress (annotations + locked accesses)
    gcs = (REPO / "ray_tpu" / "core" / "gcs.py").read_text()
    assert "# guarded-by: _lock" in gcs
    cluster = (REPO / "ray_tpu" / "core" / "cluster.py").read_text()
    assert "# guarded-by: _lock" in cluster
    result = run(Project(REPO), rules=["lock-discipline"])
    assert result.findings == [], [f.location for f in result.findings]
