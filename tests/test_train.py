"""Train layer: sharded state, train step convergence, checkpoint, controller."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

import ray_tpu
from ray_tpu.models import get_config
from ray_tpu.parallel import MeshSpec, build_mesh, default_rules
from ray_tpu.train import (
    CheckpointConfig,
    FailureConfig,
    LMTrainer,
    Result,
    RunConfig,
    RunStatus,
    ScalingConfig,
    Trainer,
    create_train_state,
    default_optimizer,
    make_train_step,
)


@pytest.fixture
def mesh8():
    return build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))


def _batches(key, n, batch, seq, vocab):
    for i in range(n):
        key, sub = jax.random.split(key)
        yield {"tokens": jax.random.randint(sub, (batch, seq + 1), 0, vocab)}


def test_state_shardings_cover_optimizer_moments(mesh8):
    config = get_config("llama-tiny")
    opt = default_optimizer(1e-3, total_steps=10)
    state, shardings = create_train_state(
        config, opt, jax.random.PRNGKey(0), mesh8, default_rules()
    )
    # adam mu/nu must inherit the param specs (fsdp/tp), not be replicated
    mu = state.opt_state[1][0].mu
    assert mu["blocks"]["w_up"].sharding.spec == PartitionSpec(None, "fsdp", "tp")
    assert state.params["blocks"]["w_up"].sharding.spec == PartitionSpec(None, "fsdp", "tp")
    # scalars replicated
    assert state.step.sharding.spec == PartitionSpec()


def test_train_step_reduces_loss(mesh8):
    config = get_config("gpt2-tiny")
    opt = default_optimizer(1e-2, warmup_steps=2, total_steps=40)
    state, shardings = create_train_state(
        config, opt, jax.random.PRNGKey(0), mesh8, default_rules()
    )
    step = make_train_step(config, opt, mesh8, state_shardings=shardings)
    # one fixed batch: loss must drop when overfitting it
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, config.vocab_size)}
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]
    assert int(state.step) == 30


def test_grad_accum_matches_big_batch(mesh8):
    config = get_config("gpt2-tiny")
    opt = default_optimizer(1e-3, total_steps=10)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 17), 0, config.vocab_size)

    state1, sh = create_train_state(config, opt, jax.random.PRNGKey(0), mesh8)
    step1 = make_train_step(config, opt, mesh8, state_shardings=sh)
    state1, m1 = step1(state1, {"tokens": tokens})

    state2, sh2 = create_train_state(config, opt, jax.random.PRNGKey(0), mesh8)
    step2 = make_train_step(config, opt, mesh8, state_shardings=sh2, grad_accum=2)
    state2, m2 = step2(state2, {"tokens": tokens})

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    p1 = jax.tree.leaves(state1.params)[0]
    p2 = jax.tree.leaves(state2.params)[0]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), atol=1e-5)


def test_lm_trainer_with_checkpoint_resume(tmp_path, mesh8):
    config = get_config("gpt2-tiny")
    ckpt = CheckpointConfig(checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=5)
    trainer = LMTrainer(
        config,
        mesh_spec=MeshSpec(dp=2, fsdp=2, tp=2),
        learning_rate=1e-3,
        total_steps=10,
        checkpoint_config=ckpt,
    )
    metrics = trainer.train(
        _batches(jax.random.PRNGKey(0), 10, 8, 16, config.vocab_size),
        num_steps=10,
        report_every=5,
    )
    assert metrics["step"] == 10
    assert metrics["tokens_per_sec"] > 0
    assert trainer.ckpt_mgr.latest_step() == 10

    # new trainer resumes from step 10
    trainer2 = LMTrainer(
        config,
        mesh_spec=MeshSpec(dp=2, fsdp=2, tp=2),
        learning_rate=1e-3,
        total_steps=10,
        checkpoint_config=ckpt,
    )
    restored = trainer2.maybe_restore()
    assert restored == 10
    p1 = jax.tree.leaves(trainer.state.params)[0]
    p2 = jax.tree.leaves(trainer2.state.params)[0]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))


def test_gang_trainer_reports_and_finishes(runtime):
    def loop(config):
        from ray_tpu import train

        ctx = train.get_context()
        for i in range(3):
            train.report({"step": i, "rank": ctx.world_rank})
        return "done"

    trainer = Trainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t1"),
        train_loop_config={},
    )
    result = trainer.fit()
    assert result.status == RunStatus.FINISHED
    assert len(result.metrics_history) == 3  # rank-0 reports only
    assert result.metrics["step"] == 2


def test_gang_trainer_failure_fast(runtime):
    def loop(config):
        from ray_tpu import train

        train.report({"step": 0})
        raise RuntimeError("worker exploded")

    trainer = Trainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", failure=FailureConfig(max_failures=0)),
        train_loop_config={},
    )
    result = trainer.fit()
    assert result.status == RunStatus.ERRORED
    assert "exploded" in result.error


def test_gang_trainer_restarts_then_succeeds(runtime, tmp_path):
    marker = tmp_path / "attempt"

    def loop(config):
        from ray_tpu import train

        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n == 0:
            raise RuntimeError("first attempt dies")
        train.report({"attempt": n})
        return "ok"

    trainer = Trainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3", failure=FailureConfig(max_failures=2)),
        train_loop_config={},
    )
    result = trainer.fit()
    assert result.status == RunStatus.FINISHED
    assert result.num_restarts == 1
    assert result.metrics["attempt"] == 1


def test_elastic_gang_resizes_on_capacity(runtime):
    """Elastic scaling (reference v2 ScalingPolicy): with part of the
    cluster occupied the gang starts small; after capacity returns, the
    restart grows it back and training resumes from the checkpoint."""
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )
    from ray_tpu.train.session import get_context, report

    @ray_tpu.remote
    class Blocker:
        def ping(self):
            return "ok"

    blockers = [Blocker.options(num_cpus=1).remote() for _ in range(5)]
    ray_tpu.get([b.ping.remote() for b in blockers], timeout=30)

    def train_fn(config=None):
        ctx = get_context()
        if ctx.world_size < 4:
            if ctx.world_rank == 0:
                for b in blockers:
                    ray_tpu.kill(b)  # capacity comes back
            report({"loss": 1.0}, checkpoint_step=5)
            raise RuntimeError("partial-capacity attempt dies")
        report({"loss": 0.5}, checkpoint_step=10)

    controller = TrainController(
        train_fn,
        ScalingConfig(num_workers=4, min_workers=1),
        RunConfig(name="elastic", failure=FailureConfig(max_failures=2)),
    )
    result = controller.run()
    assert result.status == RunStatus.FINISHED
    assert controller.world_sizes[0] < 4  # degraded start
    assert controller.world_sizes[-1] == 4  # grew back after restart
    assert result.checkpoint_step == 10
    assert result.num_restarts >= 1
