"""Training forensics plane (train/steplog.py): per-rank step-level
timelines, exact-sum step-time decomposition, cross-rank skew.

The load-bearing drills:
- the exact-sum invariant: every SEALED sampled step's phase buckets
  sum exactly to its measured step wall time, by construction (the
  ``other`` seal is the remainder);
- sampling is opt-in and cheap: with the recorder off the module mark
  is a no-op and the trainer records nothing; with ``sample_every=N``
  only every N-th step pays the sync + marks;
- skew attribution: one rank's injected slow input pipeline makes the
  skew matrix AND the stall watchdog WARNING name that rank with
  dominant bucket ``data_wait``;
- marks federate into the GCS ``_steps`` table on the stats piggyback
  and the state queries join them cluster-wide with semantic dedup.
"""

import threading
import time

import jax
import pytest

import ray_tpu
from ray_tpu.core.config import cfg
from ray_tpu.models import get_config
from ray_tpu.parallel import MeshSpec
from ray_tpu.train import LMTrainer, steplog


@pytest.fixture(autouse=True)
def _clean_steplog():
    steplog.log().clear()
    yield
    steplog.log().clear()
    cfg.reset()


def _batches(key, n, batch, seq, vocab):
    for _ in range(n):
        key, sub = jax.random.split(key)
        yield {"tokens": jax.random.randint(sub, (batch, seq + 1), 0, vocab)}


def _step_record(run, rank, step, *, data_wait=0.002, fwd_bwd=0.01,
                 ts=None):
    """A hand-built sampled-step record shaped like the trainer's
    `_steplog` payload entries."""
    buckets = {
        "data_wait": data_wait,
        "h2d": 0.001,
        "fwd_bwd_compute": fwd_bwd,
        "dp_sync": 0.0,
        "optimizer_update": 0.0,
        "ckpt_save": 0.0,
        "report": 0.001,
        "other": 0.0005,
    }
    return {
        "run": run, "rank": rank, "step": step,
        "node": None, "ts": time.time() if ts is None else ts,
        "wall_s": sum(buckets.values()), "buckets": buckets,
    }


# ------------------------------------------------------------ recorder core


def test_mark_records_both_clocks_and_seals_on_other():
    sl = steplog.StepLog()
    rec = sl.mark("data_wait", 0.25, run="r1", rank=0, step=3)
    assert rec["run"] == "r1" and rec["rank"] == 0 and rec["step"] == 3
    assert rec["phase"] == "data_wait" and rec["dur_s"] == 0.25
    assert rec["ts"] > 0 and rec["mono"] > 0 and rec["seq"] == 1
    # dup (run, rank, step, phase) dropped — what makes ingest idempotent
    assert sl.mark("data_wait", 0.99, run="r1", rank=0, step=3) is None
    (summary,) = sl.steps()
    assert summary["sealed"] is False and summary["wall_s"] is None
    sl.mark("fwd_bwd_compute", 0.50, run="r1", rank=0, step=3)
    sl.mark("other", 0.05, run="r1", rank=0, step=3, wall_s=0.80)
    (summary,) = sl.steps()
    assert summary["sealed"] is True
    assert summary["wall_s"] == 0.80  # the seal's measured wall wins
    assert summary["buckets"]["other"] == 0.05
    # a seal WITHOUT wall_s: wall is the bucket sum by definition
    sl.mark("data_wait", 0.1, run="r1", rank=0, step=4)
    sl.mark("other", 0.2, run="r1", rank=0, step=4)
    s4 = next(s for s in sl.steps() if s["step"] == 4)
    assert s4["wall_s"] == pytest.approx(0.3)


def test_ring_and_index_eviction_and_since_cursor():
    sl = steplog.StepLog(mark_capacity=8, step_capacity=4)
    for i in range(20):
        sl.mark("data_wait", 0.01, run="r", rank=0, step=i)
    stats = sl.stats()
    assert stats["buffered_marks"] == 8
    assert stats["indexed_steps"] == 4
    assert stats["seq"] == 20
    assert {s["step"] for s in sl.steps()} == {16, 17, 18, 19}
    assert sl.timeline("r") and sl.timeline("r")[0]["step"] == 12
    batch = sl.since(0, max_n=3)
    assert [m["seq"] for m in batch] == [13, 14, 15]  # oldest-first walk
    rest = sl.since(batch[-1]["seq"], max_n=10)
    assert [m["seq"] for m in rest] == [16, 17, 18, 19, 20]
    assert sl.since(20) == []


def test_ingest_dedups_and_summarize_rebuilds():
    sl = steplog.StepLog()
    recs = [_step_record("fed", 0, 1), _step_record("fed", 1, 1,
                                                    data_wait=0.4)]
    accepted = sl.ingest(recs)
    assert len(accepted) == 2
    # the same records again (the in-process-gang double path): no-op
    assert sl.ingest(recs) == []
    summaries = sl.steps(run="fed")
    assert len(summaries) == 2 and all(s["sealed"] for s in summaries)
    for s in summaries:
        assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"])
    # a federated consumer rebuilds the same summaries from raw marks
    rebuilt = {(s["rank"], s["step"]): s
               for s in steplog.summarize_steps(sl.since(0))}
    assert rebuilt[(1, 1)]["buckets"]["data_wait"] == pytest.approx(0.4)
    assert rebuilt[(1, 1)]["sealed"] is True
    # malformed records are skipped, not fatal
    assert sl.ingest([{"run": "x"}, "not-a-dict", None]) == []


def test_module_mark_is_noop_when_disabled_and_registry_idempotent():
    before = steplog.log().stats()["seq"]
    cfg.set(train_step_log=False)
    try:
        assert not steplog.enabled()
        steplog.mark("data_wait", 0.1, run="dark", rank=0, step=1)
        assert steplog.log().stats()["seq"] == before
    finally:
        cfg.reset()
    assert steplog.enabled()
    steplog.mark("data_wait", 0.1, run="lit", rank=0, step=1)
    assert steplog.log().stats()["seq"] == before + 1
    steplog.register_step_phase("test.custom", "a drill phase")
    steplog.register_step_phase("test.custom", "overwrite ignored")
    assert steplog.step_phases()["test.custom"] == "a drill phase"
    del steplog.STEP_PHASES["test.custom"]
    assert steplog.SEAL_PHASE in steplog.STEP_PHASES


# ------------------------------------------------- trainer instrumentation


def test_sampled_steps_exact_sum_sampling_gate_and_off_switch():
    """THE invariant: every sealed summary's buckets sum EXACTLY to the
    recorded step wall time (the seal is the remainder by construction;
    approx() covers float addition only). One trainer (one compile)
    drives three phases: sample_every=1, sample_every=4, recorder off."""
    cfg.set(step_log_sample_every=1)
    config = get_config("gpt2-tiny")
    trainer = LMTrainer(config, mesh_spec=MeshSpec(dp=2, fsdp=2, tp=2),
                        learning_rate=1e-3, total_steps=24)
    trainer.train(
        _batches(jax.random.PRNGKey(0), 8, 8, 16, config.vocab_size),
        num_steps=8, report_every=4, run_name="exact-run",
    )
    summaries = steplog.log().steps(run="exact-run")
    assert len(summaries) == 8  # sample_every=1: every step decomposed
    for s in summaries:
        assert s["sealed"], s
        assert set(s["buckets"]) == set(steplog.STEP_PHASES)
        assert all(v >= 0.0 for v in s["buckets"].values()), s["buckets"]
        assert sum(s["buckets"].values()) == pytest.approx(
            s["wall_s"], rel=1e-9, abs=1e-12)
        # real work landed in the real buckets
        assert s["buckets"]["fwd_bwd_compute"] > 0.0
    # single-replica mesh (dp=2 but CPU single process): dp_sync is the
    # wire-byte estimate, capped at device time, and flagged estimated
    tl = steplog.log().timeline("exact-run")
    dp_marks = [m for m in tl if m["phase"] == "dp_sync"]
    assert dp_marks and all(m["attrs"]["estimated"] for m in dp_marks)

    # sampling gate: only every sample_every-th step is decomposed
    cfg.set(step_log_sample_every=4)
    trainer.train(
        _batches(jax.random.PRNGKey(1), 8, 8, 16, config.vocab_size),
        num_steps=8, report_every=4, run_name="sampled-run",
    )
    sampled = steplog.log().steps(run="sampled-run")
    assert len(sampled) == 2  # loop steps 0 and 4 of 8

    # recorder off: the identical loop records NOTHING
    cfg.set(train_step_log=False)
    before = steplog.log().stats()["seq"]
    trainer.train(
        _batches(jax.random.PRNGKey(2), 8, 8, 16, config.vocab_size),
        num_steps=8, report_every=4, run_name="dark-run",
    )
    assert steplog.log().stats()["seq"] == before
    assert steplog.log().steps(run="dark-run") == []


# ------------------------------------------------------- skew + waterfall


def test_skew_matrix_and_dominant_bucket_name_the_slow_rank():
    sl = steplog.StepLog()
    sl.ingest([
        _step_record("skew", 0, 5, data_wait=0.002),
        _step_record("skew", 1, 5, data_wait=0.450),  # slow input pipe
        _step_record("skew", 0, 6),
    ])
    rows = steplog.skew_matrix(sl.steps(run="skew"))
    two_rank = next(r for r in rows if r["step"] == 5)
    assert two_rank["ranks"] == [0, 1]
    assert two_rank["straggler_rank"] == 1
    assert two_rank["dominant_bucket"] == "data_wait"
    assert two_rank["dominant_excess_s"] == pytest.approx(0.448)
    assert two_rank["spread_s"] == pytest.approx(0.448)
    single = next(r for r in rows if r["step"] == 6)
    assert single["ranks"] == [0] and single["straggler_rank"] == 0

    text = steplog.render_waterfall(sl.steps(run="skew"))
    lines = text.splitlines()
    assert "run skew" in lines[0] and "rank(s) 0,1" in lines[0]
    assert "legend:" in lines[1] and "d=data_wait" in lines[1]
    # one bar per (step, rank), Σ column proving the exact sum
    bars = [l for l in lines if "|" in l]
    assert len(bars) == 3
    for bar in bars:
        assert "wall" in bar and "Σ" in bar
    # the skew footer names the straggler + dominant bucket
    assert any("skew: straggler rank 1" in l
               and "dominant data_wait" in l for l in lines)
    assert steplog.render_waterfall([]) == "(no sampled steps)"


def test_straggler_drill_warning_names_rank_and_data_wait():
    """Acceptance: a gang whose rank 1 has an injected slow input
    pipeline. Its sampled-step records ride the report plane; when the
    stall fires, the watchdog WARNING names rank 1 AND the dominant
    bucket data_wait (fed by the controller's _observe_step_records)."""
    from ray_tpu import train
    from ray_tpu.train import RunConfig, ScalingConfig, TrainController
    from ray_tpu.util.events import events

    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    cfg.set(train_stall_window_s=60.0,  # global window off the hot path
            train_stall_factor=4.0, train_stall_min_s=0.25,
            train_stall_ewma_alpha=0.3)
    run_name = "skew_drill"

    def train_fn(config):
        import time as _t

        ctx = train.get_context()
        rank = ctx.world_rank
        slow = rank == 1
        for step in range(25):
            rec = {
                "run": "skew_drill", "rank": rank, "step": step,
                "node": None, "ts": _t.time(),
                "wall_s": 0.5 if slow else 0.02,
                "buckets": {
                    "data_wait": 0.45 if slow else 0.002,
                    "h2d": 0.001,
                    "fwd_bwd_compute": 0.01,
                    "dp_sync": 0.0, "optimizer_update": 0.0,
                    "ckpt_save": 0.0, "report": 0.001,
                    "other": (0.5 - 0.462) if slow else (0.02 - 0.014),
                },
            }
            train.report({"step": step, "_steplog": [rec],
                          "_mono": _t.perf_counter()})
            if slow and step == 10:
                _t.sleep(1.2)  # the injected stall: EWMA regression
            else:
                _t.sleep(0.03)

    controller = TrainController(
        train_fn,
        ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1.0}),
        RunConfig(name=run_name),
        train_config={},
        poll_interval=0.02,
    )
    result_box = {}
    t = threading.Thread(
        target=lambda: result_box.setdefault("result", controller.run()),
        daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 30
        warned = []
        while time.monotonic() < deadline and not warned:
            warned = [
                e for e in events().list(severity="WARNING",
                                         source="watchdog", limit=200)
                if run_name in e["message"] and "STALLED" in e["message"]
            ]
            time.sleep(0.02)
        assert warned, "stall watchdog never fired on the slow-input rank"
        msg = warned[0]["message"]
        assert "rank 1" in msg, msg
        assert "dominant bucket data_wait" in msg, msg
        assert warned[0].get("extra", {}).get("dominant_bucket") \
            == "data_wait"
        t.join(timeout=60)
        assert not t.is_alive()
        assert result_box["result"].status.value == "FINISHED", (
            result_box["result"].error
        )
        # the controller re-rang the gang's records: the skew matrix
        # over its steps names the same rank + bucket, every sampled step
        rows = steplog.skew_matrix(steplog.log().steps(run=run_name,
                                                       limit=1000))
        two_rank = [r for r in rows if len(r["ranks"]) == 2]
        assert two_rank, "no cross-rank step pairs reached the controller"
        assert all(r["straggler_rank"] == 1 for r in two_rank)
        assert all(r["dominant_bucket"] == "data_wait" for r in two_rank)
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- federation


def test_step_marks_federate_and_state_queries():
    from ray_tpu.core.gcs import STEPLOG_NS
    from ray_tpu.util import state

    rt = ray_tpu.init(num_cpus=1, head=True, detect_accelerators=False)
    try:
        ctx = rt.cluster
        my_hex = ctx.node_id.hex()
        steplog.log().ingest([
            _step_record("fed-run", 0, 1, data_wait=0.002),
            _step_record("fed-run", 1, 1, data_wait=0.300),
            _step_record("other-run", 0, 7),
        ])
        prev, tail = -1, []
        while len(tail) != prev:
            prev = len(tail)
            ctx._last_stats_ts = 0.0
            ctx._report_stats()
            tail = ctx.gcs.kv_get(my_hex, namespace=STEPLOG_NS) or []
        assert tail, "no marks federated into the _steps table"
        assert all(m.get("node") for m in tail)
        # cursor advanced: another pass without new marks is a no-op
        before = len(tail)
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        assert len(ctx.gcs.kv_get(my_hex, namespace=STEPLOG_NS)) == before
        # the state queries join local ring ∪ federated table with
        # SEMANTIC dedup (run, rank, step, phase)
        summaries = state.step_timeline("fed-run")
        assert [(s["rank"], s["step"]) for s in summaries] == [(0, 1), (1, 1)]
        for s in summaries:
            assert s["sealed"]
            assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"])
        rows = state.list_steps()
        runs = {s["run"] for s in rows}
        assert {"fed-run", "other-run"} <= runs
        assert [s["run"] for s in state.list_steps(run="other-run")] \
            == ["other-run"]
        skew = state.step_skew("fed-run")
        assert skew and skew[0]["straggler_rank"] == 1
        assert skew[0]["dominant_bucket"] == "data_wait"
        # federation lag drains to zero once the cursor caught up
        assert ctx._federation_lag().get("steps", 0) == 0
        # a federated recorder off-switch: no new marks ship
        cfg.set(train_step_log=False)
        steplog.log().mark("data_wait", 0.1, run="dark-fed", rank=0, step=1)
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        assert not any(m["run"] == "dark-fed" for m in
                       ctx.gcs.kv_get(my_hex, namespace=STEPLOG_NS))
    finally:
        cfg.reset()
        ray_tpu.shutdown()


def test_steplog_table_is_bounded():
    from ray_tpu.core.gcs import STEPLOG_NS

    rt = ray_tpu.init(num_cpus=1, head=True, detect_accelerators=False)
    cfg.set(steplog_table_cap=20, steplog_federate_batch=500)
    try:
        ctx = rt.cluster
        for i in range(80):
            steplog.mark("data_wait", 0.01, run="burst", rank=0, step=i)
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        tail = ctx.gcs.kv_get(ctx.node_id.hex(), namespace=STEPLOG_NS)
        assert len(tail) <= 20
        assert tail[-1]["step"] == 79  # newest survive
    finally:
        cfg.reset()
        ray_tpu.shutdown()
