"""RL library: env physics, rollout machinery, PPO learning
(reference: rllib/algorithms/ppo, rllib/env/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import CartPoleVectorEnv, PPOConfig, register_env


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


def test_cartpole_env_basics():
    env = CartPoleVectorEnv(num_envs=4)
    obs = env.reset(seed=0)
    assert obs.shape == (4, 4)
    total_dones = 0
    for _ in range(300):
        obs, rewards, dones = env.step(np.random.randint(0, 2, size=4))
        assert rewards.shape == (4,) and (rewards == 1.0).all()
        total_dones += int(dones.sum())
    # random policy fails well before 300 steps: every lane reset at least once
    assert total_dones >= 4
    assert np.isfinite(obs).all()


def test_random_policy_baseline_short_episodes():
    env = CartPoleVectorEnv(num_envs=8)
    env.reset(seed=1)
    lengths = []
    steps = np.zeros(8)
    for _ in range(500):
        _, _, dones = env.step(np.random.randint(0, 2, size=8))
        steps += 1
        for i in np.nonzero(dones)[0]:
            lengths.append(steps[i])
            steps[i] = 0
    assert 5 < np.mean(lengths) < 60  # classic random-CartPole range


def test_ppo_learns_cartpole():
    """The end-to-end RL story: PPO must clearly beat the random baseline."""
    algo = PPOConfig(
        env="cartpole", num_workers=2, num_envs_per_worker=8,
        rollout_len=128, lr=3e-3, num_epochs=4, seed=0,
    ).build()
    try:
        first = None
        result = None
        for _ in range(25):
            result = algo.train()
            if first is None and result["episodes_this_iter"] > 0:
                first = result["episode_reward_mean"]
        assert result["training_iteration"] == 25
        assert result["timesteps_this_iter"] == 2 * 8 * 128
        # random CartPole averages ~20; learning must at least double it
        # and clear 60 outright
        assert result["episode_reward_mean"] > max(60.0, 2 * first), (
            first, result["episode_reward_mean"]
        )
    finally:
        algo.stop()


def test_custom_env_registration():
    class ConstantEnv(CartPoleVectorEnv):
        pass

    register_env("constant", lambda n: ConstantEnv(n))
    algo = PPOConfig(env="constant", num_workers=1, num_envs_per_worker=2,
                     rollout_len=8).build()
    try:
        result = algo.train()
        assert result["timesteps_this_iter"] == 16
    finally:
        algo.stop()

    with pytest.raises(ValueError, match="unknown env"):
        PPOConfig(env="nope").build()
