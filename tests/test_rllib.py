"""RL library: env physics, rollout machinery, PPO learning
(reference: rllib/algorithms/ppo, rllib/env/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import CartPoleVectorEnv, PPOConfig, register_env


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


def test_cartpole_env_basics():
    env = CartPoleVectorEnv(num_envs=4)
    obs = env.reset(seed=0)
    assert obs.shape == (4, 4)
    total_dones = 0
    for _ in range(300):
        obs, rewards, dones = env.step(np.random.randint(0, 2, size=4))
        assert rewards.shape == (4,) and (rewards == 1.0).all()
        total_dones += int(dones.sum())
    # random policy fails well before 300 steps: every lane reset at least once
    assert total_dones >= 4
    assert np.isfinite(obs).all()


def test_random_policy_baseline_short_episodes():
    env = CartPoleVectorEnv(num_envs=8)
    env.reset(seed=1)
    lengths = []
    steps = np.zeros(8)
    for _ in range(500):
        _, _, dones = env.step(np.random.randint(0, 2, size=8))
        steps += 1
        for i in np.nonzero(dones)[0]:
            lengths.append(steps[i])
            steps[i] = 0
    assert 5 < np.mean(lengths) < 60  # classic random-CartPole range


def test_ppo_learns_cartpole():
    """The end-to-end RL story: PPO must clearly beat the random baseline."""
    algo = PPOConfig(
        env="cartpole", num_workers=2, num_envs_per_worker=8,
        rollout_len=128, lr=3e-3, num_epochs=4, seed=0,
    ).build()
    try:
        first = None
        result = None
        for _ in range(25):
            result = algo.train()
            if first is None and result["episodes_this_iter"] > 0:
                first = result["episode_reward_mean"]
        assert result["training_iteration"] == 25
        assert result["timesteps_this_iter"] == 2 * 8 * 128
        # random CartPole averages ~20; learning must at least double it
        # and clear 60 outright
        assert result["episode_reward_mean"] > max(60.0, 2 * first), (
            first, result["episode_reward_mean"]
        )
    finally:
        algo.stop()


def test_custom_env_registration():
    class ConstantEnv(CartPoleVectorEnv):
        pass

    register_env("constant", lambda n: ConstantEnv(n))
    algo = PPOConfig(env="constant", num_workers=1, num_envs_per_worker=2,
                     rollout_len=8).build()
    try:
        result = algo.train()
        assert result["timesteps_this_iter"] == 16
    finally:
        algo.stop()

    with pytest.raises(ValueError, match="unknown env"):
        PPOConfig(env="nope").build()


def test_dqn_learns_cartpole():
    """The off-policy family: double-DQN with replay must clearly beat
    the random baseline (reference rllib/algorithms/dqn)."""
    from ray_tpu.rllib import DQNConfig

    algo = DQNConfig(
        env="cartpole", num_workers=2, num_envs_per_worker=8,
        rollout_len=64, lr=1e-3, updates_per_iter=48,
        learning_starts=512, eps_decay_iters=12, seed=0,
    ).build()
    try:
        result = None
        recent = []
        for _ in range(30):
            result = algo.train()
            if result["episodes_this_iter"] > 0:
                recent.append(result["episode_reward_mean"])
        assert result["training_iteration"] == 30
        assert result["buffer_size"] > 512
        assert result["num_updates"] > 0
        # random CartPole averages ~20; the late-training mean must
        # clearly clear it (DQN is noisier than PPO, so average the tail)
        tail = float(np.mean(recent[-5:]))
        assert tail > 60.0, (recent[:5], recent[-5:])
    finally:
        algo.stop()


def test_dqn_replay_buffer_ring():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=10, obs_dim=2)
    batch = {
        "obs": np.arange(8).reshape(4, 2).astype(np.float32),
        "next_obs": np.zeros((4, 2), np.float32),
        "actions": np.arange(4, dtype=np.int32),
        "rewards": np.ones(4, np.float32),
        "dones": np.zeros(4, np.bool_),
    }
    for _ in range(4):  # 16 adds into capacity 10: wraps
        buf.add(batch)
    assert buf.size == 10
    s = buf.sample(np.random.default_rng(0), 6)
    assert s["obs"].shape == (6, 2) and s["dones"].dtype == np.float32
