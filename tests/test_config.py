"""Central config flag registry (reference: common/ray_config_def.h +
RAY_<name> env overrides, ray_config.h:104)."""

import pytest

from ray_tpu.core.config import RayTpuConfig, _REGISTRY, cfg


def test_defaults_and_registry():
    c = RayTpuConfig()
    assert c.object_store_capacity_bytes == 8 << 30
    assert c.native_store is False
    assert c.inline_max_bytes == 100 * 1024
    # every flag is typed + documented
    for flag in _REGISTRY.values():
        assert flag.doc
        assert isinstance(flag.default, flag.type)


def test_env_override(monkeypatch):
    c = RayTpuConfig()
    monkeypatch.setenv("RAY_TPU_OBJECT_STORE_CAPACITY_BYTES", "1e6")
    assert c.object_store_capacity_bytes == 1_000_000
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "true")
    assert c.native_store is True
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "off")
    assert c.native_store is False
    # unknown tokens degrade to truthy-with-warning, not a crash at init
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "bogus")
    assert c.native_store is True


def test_set_overrides_beat_env(monkeypatch):
    c = RayTpuConfig()
    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_FAILURES", "7")
    assert c.health_check_failures == 7
    c.set(health_check_failures=2)
    assert c.health_check_failures == 2
    c.reset("health_check_failures")
    assert c.health_check_failures == 7


def test_unknown_flag_rejected():
    c = RayTpuConfig()
    with pytest.raises(ValueError, match="unknown config flag"):
        c.set(definitely_not_a_flag=1)
    with pytest.raises(AttributeError):
        _ = c.definitely_not_a_flag


def test_type_coercion_and_mismatch():
    c = RayTpuConfig()
    c.set(gcs_snapshot_interval_s=2)  # int ok where float expected
    assert c.gcs_snapshot_interval_s == 2.0
    with pytest.raises(ValueError, match="expects"):
        c.set(max_process_workers="not-a-number")
    c.reset()


def test_describe_lists_every_flag():
    text = cfg.describe()
    for name in _REGISTRY:
        assert name in text


def test_store_reads_flags(monkeypatch, tmp_path):
    monkeypatch.setenv("RAY_TPU_INLINE_MAX_BYTES", "10")
    from ray_tpu.core.ids import JobID, ObjectID
    from ray_tpu.core.object_store import ObjectStore, Tier

    store = ObjectStore()
    oid = ObjectID.for_put(JobID.next())
    store.put(oid, b"x" * 100)  # > 10 bytes -> host tier, not inline
    assert store.entry(oid).tier == Tier.HOST
