"""Structured events, cross-node log aggregation, and wire-protocol
gating (reference: util/events framework, `ray logs` via per-node
dashboard agents, and proto-versioned RPC membership).
"""

import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.events import EventLog, events


def test_event_log_emit_filter_and_ring():
    log = EventLog(capacity=5)
    for i in range(8):
        log.emit("INFO" if i % 2 else "WARNING", "test", f"e{i}", k=i)
    out = log.list()
    assert len(out) == 5  # ring capacity
    assert out[-1]["message"] == "e7"
    warnings = log.list(severity="WARNING")
    assert all(e["severity"] == "WARNING" for e in warnings)
    assert log.list(since_seq=out[-1]["seq"]) == []
    assert out[-1]["extra"] == {"k": 7}


def test_event_jsonl_sink(tmp_path):
    import json

    path = str(tmp_path / "events.jsonl")
    log = EventLog(sink_path=path)
    log.emit("ERROR", "test", "boom", code=3)
    rec = json.loads(open(path).read().strip())
    assert rec["severity"] == "ERROR" and rec["extra"]["code"] == 3


@pytest.fixture
def cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(2)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_cluster_logs_and_events_span_nodes(cluster):
    """Every node's log tail and event tail are fetchable from the
    driver; agent-side activity shows up in the agent's buffers."""
    from ray_tpu.util import state

    @ray_tpu.remote(num_cpus=1)
    def noisy():
        import logging

        logging.getLogger("ray_tpu.test").warning("agent-side line")
        return 1

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    agent = next(
        n for n in cluster.runtime.scheduler.nodes() if n.is_remote
    )
    assert ray_tpu.get(
        noisy.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(agent.node_id)
        ).remote(),
        timeout=60,
    ) == 1

    logs = state.cluster_logs(tail=100)
    assert len(logs) == 2  # head + agent
    agent_lines = logs[agent.node_id.hex()]
    assert any("agent-side line" in line for line in agent_lines)

    # the AGENT discovered the head: a cluster discovery event exists on
    # the agent side (emitted by its own _refresh_nodes tick — poll for
    # it, the tick runs on the heartbeat cadence)
    deadline = time.monotonic() + 30
    found = False
    while time.monotonic() < deadline and not found:
        evs = state.cluster_events()
        assert len(evs) == 2
        found = any(
            e["source"] == "cluster" and "discovered" in e["message"]
            for e in evs[agent.node_id.hex()]
        )
        if not found:
            time.sleep(0.2)
    assert found, evs[agent.node_id.hex()]


def test_cli_logs_and_events(cluster):
    env = {"JAX_PLATFORMS": "cpu"}
    import os

    for cmd, needle in (("logs", "=== node"), ("events", "discovered")):
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", cmd,
             "--address", cluster.address],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, **env},
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert needle in out.stdout, (cmd, out.stdout[-500:])


def test_protocol_mismatch_refuses_join(cluster):
    """A node speaking a different wire-protocol generation must refuse
    to join with an actionable error instead of desyncing (rpc.py
    PROTOCOL_VERSION)."""
    # forge a future protocol version into the head's GCS
    cluster.runtime.cluster.gcs.kv_put("version", 999, namespace="_protocol")
    handle = cluster.add_node(num_cpus=1)
    deadline = time.monotonic() + 60
    while handle.proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.2)
    assert handle.proc.poll() is not None, "mismatched agent kept running"
    log = open(handle.log_path).read()
    assert "wire protocol 999" in log, log[-800:]
