"""Cluster launcher: `ray_tpu up/down <config>` over the provider
abstraction (reference: `ray up`, autoscaler/_private/commands.py).
"""

import json
import os
import subprocess
import sys
import time

import pytest


def _write_config(tmp_path, n_workers=2):
    config = {
        "head": {"port": 0, "num_cpus": 1},
        "workers": [
            {"host": "localhost", "num_cpus": 2,
             "resources": {"pet": 1}}
            for _ in range(n_workers)
        ],
        "provider": "local",
    }
    path = tmp_path / "cluster.yaml"
    import yaml

    path.write_text(yaml.safe_dump(config))
    return str(path), config


def test_up_launches_and_down_terminates(tmp_path):
    from ray_tpu.launcher import ClusterLauncher, load_config

    # port 0 is invalid for a rendezvous address: pick a free one
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    path, config = _write_config(tmp_path)
    config["head"]["port"] = port
    launcher = ClusterLauncher(config, no_tpu=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        info = launcher.up(wait_s=90)
        assert info["address"].endswith(f":{port}")
        assert len(info["nodes"]) == 3
        # a driver can use the launched cluster
        import ray_tpu

        ray_tpu.init(address=info["address"], num_cpus=0,
                     detect_accelerators=False)
        deadline = time.monotonic() + 60
        while ray_tpu.cluster_resources().get("pet", 0) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.2)

        @ray_tpu.remote(num_cpus=0, resources={"pet": 1})
        def where():
            return os.getpid()

        pid = ray_tpu.get(where.remote(), timeout=60)
        assert pid in [n["pid"] for n in info["nodes"]]
        ray_tpu.shutdown()
    finally:
        launcher.down()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in launcher.provider.procs):
            break
        time.sleep(0.2)
    assert all(p.poll() is not None for p in launcher.provider.procs)


def test_ssh_provider_command_construction():
    from ray_tpu.launcher import SSHLaunchProvider, _start_cmd

    provider = SSHLaunchProvider({
        "ssh_user": "me", "workers": [{"host": "10.0.0.2"}],
    })
    cmd = _start_cmd(
        address="10.0.0.1:6379", port=None, num_cpus=8,
        resources={"TPU": 4}, token="sekrit", no_tpu=False,
    )
    full = provider.ssh_command("10.0.0.2", cmd)
    assert full[0] == "ssh"
    assert "me@10.0.0.2" in full
    remote = full[-1]
    assert "--address 10.0.0.1:6379" in remote
    assert "--num-cpus 8" in remote
    assert "--token sekrit" in remote
    assert remote.startswith("nohup ")
    assert "'{\"TPU\": 4}'" in remote  # resources JSON is shell-quoted


def test_unknown_provider_rejected():
    from ray_tpu.launcher import ClusterLauncher

    with pytest.raises(ValueError, match="unknown provider"):
        ClusterLauncher({"provider": "gcp"})


def test_cli_up_down_roundtrip(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    config = {
        "head": {"port": port, "num_cpus": 1},
        "workers": [{"host": "localhost", "num_cpus": 1}],
        "provider": "local",
    }
    path = tmp_path / "c.json"
    path.write_text(json.dumps(config))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    up = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "--no-tpu", "up", str(path)],
        capture_output=True, text=True, timeout=180, env=env,
    )
    try:
        assert up.returncode == 0, up.stdout + up.stderr
        assert "cluster up: 2 nodes" in up.stdout
    finally:
        down = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "down", str(path)],
            capture_output=True, text=True, timeout=60, env=env,
        )
    assert down.returncode == 0, down.stdout + down.stderr
    assert "stopped 2 nodes" in down.stdout


def test_ssh_provider_lifecycle_fake_transport(tmp_path):
    """Drive the ssh provider through a REAL up→join→down lifecycle over
    a loopback transport: a fake `ssh` binary records every invocation
    and executes the remote command locally, so agents actually start,
    register with the head's GCS, and die on `down` — the provider is
    exercised end to end, not just its argv assembly."""
    import socket

    from ray_tpu.launcher import ClusterLauncher

    record = tmp_path / "ssh_record.jsonl"
    fake = tmp_path / "fake_ssh.py"
    fake.write_text(
        "#!/usr/bin/env python3\n"
        "import json, subprocess, sys\n"
        f"with open({str(record)!r}, 'a') as f:\n"
        "    f.write(json.dumps(sys.argv[1:]) + '\\n')\n"
        "proc = subprocess.run(['/bin/sh', '-c', sys.argv[-1]],\n"
        "                      capture_output=True, text=True)\n"
        "sys.stdout.write(proc.stdout)\n"
        "sys.stderr.write(proc.stderr)\n"
        "sys.exit(proc.returncode)\n"
    )
    fake.chmod(0o755)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    config = {
        "provider": "ssh",
        "ssh_bin": str(fake),
        "head": {"host": "localhost", "port": port, "num_cpus": 1},
        "workers": [{"host": "localhost", "num_cpus": 1,
                     "resources": {"fake": 1}}],
    }
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    launcher = ClusterLauncher(config, no_tpu=True)
    try:
        info = launcher.up(wait_s=90)
        assert info["address"].endswith(f":{port}")
        assert len(info["nodes"]) == 2

        # both nodes joined the head's GCS through the fake transport
        from ray_tpu.core.gcs_service import GcsClient

        client = GcsClient(info["address"])
        try:
            view = client.cluster_view()
            assert len(view["nodes"]) == 2
            assert view["total"].get("fake", 0) == 1
        finally:
            client.close()

        launches = [json.loads(l) for l in record.read_text().splitlines()]
        assert len(launches) == 2
        assert all(a[-1].startswith("nohup ") for a in launches)
        assert "--head" in launches[0][-1]
        assert "--address" in launches[1][-1]
    finally:
        launcher.down()

    # down pkill'ed by launch tag on every configured host
    invocations = [json.loads(l) for l in record.read_text().splitlines()]
    downs = [a for a in invocations if "pkill" in a[-1]]
    assert len(downs) == 2
    tag = config["_launch_tag"]
    assert all(tag in a[-1] for a in downs)

    # ...and the cluster is actually gone: the head stops answering
    from ray_tpu.core.gcs_service import GcsClient

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            c = GcsClient(info["address"], timeout=2.0)
            try:
                c.ping()
            finally:
                c.close()
        except Exception:
            break
        time.sleep(0.3)
    else:
        raise AssertionError("head still answering after down()")
