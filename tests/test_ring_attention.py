"""Ring attention vs dense reference on an sp-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import mha_reference
from ray_tpu.ops.ring_attention import ring_attention, ring_attention_sharded
from ray_tpu.parallel import MeshSpec, build_mesh


@pytest.fixture
def sp_mesh():
    return build_mesh(MeshSpec(sp=8))


@pytest.fixture
def sp4_mesh():
    return build_mesh(MeshSpec(dp=2, sp=4))


def _qkv(key, b, h, s, d, hkv=None):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, h, s, d)),
        jax.random.normal(kk, (b, hkv or h, s, d)),
        jax.random.normal(kv, (b, hkv or h, s, d)),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(sp_mesh, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 4, 128, 32)
    expected = mha_reference(q, k, v, causal=causal)
    out = ring_attention_sharded(q, k, v, sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_ring_gqa(sp4_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 4, 64, 32, hkv=2)
    expected = mha_reference(q, k, v, causal=True)
    out = ring_attention_sharded(q, k, v, sp4_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_ring_under_jit_keeps_sharding(sp_mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 2, 64, 16)
    spec = NamedSharding(sp_mesh, P(None, None, "sp", None))
    q, k, v = (jax.device_put(x, spec) for x in (q, k, v))
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=sp_mesh, causal=True))
    out = fn(q, k, v)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v, causal=True)),
        atol=2e-5, rtol=2e-5,
    )


def test_ring_backward_matches_reference(sp_mesh):
    """Autodiff through the ring (scan + ppermute transpose)."""
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 2, 64, 16)

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, sp_mesh, causal=True)
        return jnp.sum(out * out)

    def loss_ref(q, k, v):
        out = mha_reference(q, k, v, causal=True)
        return jnp.sum(out * out)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ring_rejects_indivisible_seq(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 2, 100, 16)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=sp_mesh, causal=False)


def test_fused_matches_einsum_body(sp_mesh):
    """The fused (flash-kernel) ring body and the einsum reference body
    are the same online-softmax recurrence — outputs must agree."""
    q, k, v = _qkv(jax.random.PRNGKey(7), 2, 4, 64, 16)
    fused = ring_attention(q, k, v, mesh=sp_mesh, causal=True, impl="fused")
    ein = ring_attention(q, k, v, mesh=sp_mesh, causal=True, impl="einsum")
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ein), atol=2e-5)


def test_fused_gradients_match_dense(sp_mesh):
    """Gradients through the fused body (custom_vjp → einsum ring
    backward) must match the dense reference gradients."""
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 2, 32, 8)

    def ring_loss(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh=sp_mesh, causal=True, impl="fused") ** 2
        )

    def dense_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=1e-4)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="kernel microbench needs a real TPU")
def test_fused_local_block_beats_einsum_on_tpu():
    """The named long-context win (VERDICT r3 #4): at S_local >= 1024 the
    Pallas flash local block must beat the einsum block that materializes
    (S_local x S_local) f32 logits. Measured 1.58x on v5e at S=2048.

    Methodology for tunneled chips: N iterations are chained INSIDE one
    jit (fori_loop, each consuming the previous output) and synced by a
    single scalar host read, so the per-block time excludes the ~100 ms
    tunnel round trip that would otherwise swamp the measurement."""
    import time

    from ray_tpu.ops.attention import flash_attention_with_lse

    b, h, s, d = 4, 8, 2048, 128
    n_iters = 40
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16) for kk in keys)

    def einsum_block(q, k, v):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (d ** 0.5)
        m = jnp.max(s_, axis=-1, keepdims=True)
        p = jnp.exp(s_ - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) / l

    def chained(block):
        def f(q, k, v):
            def body(_, qq):
                return block(qq, k, v).astype(jnp.bfloat16)
            return jnp.sum(
                jax.lax.fori_loop(0, n_iters, body, q).astype(jnp.float32)
            )
        return jax.jit(f)

    fused = chained(lambda q, k, v: flash_attention_with_lse(q, k, v)[0])
    ein = chained(einsum_block)

    def bench(fn):
        float(fn(q, k, v))  # compile + sync
        t0 = time.perf_counter()
        float(fn(q, k, v))  # host read = true sync
        return (time.perf_counter() - t0) / n_iters

    t_fused, t_ein = bench(fused), bench(ein)
    assert t_fused < t_ein, f"fused {t_fused*1e3:.2f}ms !< einsum {t_ein*1e3:.2f}ms"
