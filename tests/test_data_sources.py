"""New datasources (tfrecord / image dir / binary files) and Data
running ON the cluster (round-4 verdict #9): map tasks spill to agent
nodes with blocks flowing as refs pulled where consumed.

Reference: _internal/datasource/tfrecords_datasource.py,
image_datasource.py, binary_datasource.py; task_pool_map_operator.py
dispatches cluster-wide tasks.
"""

import os
import struct
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


# ------------------------------------------------- tf.train.Example writer
# Minimal protobuf ENCODER (the parser under test lives in datasource.py;
# writing through an independent encoder makes the round-trip honest).

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # protobuf int64: two's complement in 64 bits
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _feature_int64(values) -> bytes:
    packed = b"".join(_varint(int(v)) for v in values)
    return _ld(3, _ld(1, packed))


def _feature_float(values) -> bytes:
    packed = np.asarray(values, dtype="<f4").tobytes()
    return _ld(2, _ld(1, packed))


def _feature_bytes(values) -> bytes:
    body = b"".join(_ld(1, v) for v in values)
    return _ld(1, body)


def _example(features: dict) -> bytes:
    entries = b""
    for key, feat in features.items():
        entry = _ld(1, key.encode()) + _ld(2, feat)
        entries += _ld(1, entry)
    return _ld(1, entries)


def _write_tfrecord(path: str, records) -> None:
    with open(path, "wb") as f:
        for rec in records:
            f.write(struct.pack("<Q", len(rec)))
            f.write(b"\x00" * 4)  # length crc (parser skips)
            f.write(rec)
            f.write(b"\x00" * 4)  # data crc


@pytest.fixture(autouse=True)
def runtime():
    ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


def test_read_tfrecord_examples(tmp_path):
    path = str(tmp_path / "shard-0.tfrecord")
    _write_tfrecord(path, [
        _example({
            "label": _feature_int64([i]),
            "offset": _feature_int64([-i - 1]),  # negative: sign folding
            "score": _feature_float([i * 0.5, i * 0.25]),
            "name": _feature_bytes([f"row{i}".encode()]),
        })
        for i in range(5)
    ])
    rows = rdata.read_tfrecord(path).take(1000)
    assert len(rows) == 5
    assert [int(r["label"]) for r in rows] == list(range(5))
    assert [int(r["offset"]) for r in rows] == [-1, -2, -3, -4, -5]
    assert rows[3]["score"] == pytest.approx([1.5, 0.75])
    assert rows[2]["name"] == b"row2"


def test_read_tfrecord_raw(tmp_path):
    path = str(tmp_path / "raw.tfrecord")
    _write_tfrecord(path, [b"alpha", b"beta"])
    rows = rdata.read_tfrecord(path, parse=False).take(1000)
    assert [r["bytes"] for r in rows] == [b"alpha", b"beta"]


def test_read_images_dir(tmp_path):
    from PIL import Image

    for i in range(4):
        Image.fromarray(
            np.full((8 + i, 6, 3), i * 10, dtype=np.uint8)
        ).save(tmp_path / f"img{i}.png")
    # ragged decode first: same height, DIFFERENT widths -> object column
    ragged = rdata.read_images(str(tmp_path)).take(1000)
    assert len(ragged) == 4
    assert {r["image"].shape[0] for r in ragged} == {8, 9, 10, 11}
    ds = rdata.read_images(str(tmp_path), size=(6, 8))
    rows = ds.take(1000)
    assert len(rows) == 4
    assert all(r["image"].shape == (8, 6, 3) for r in rows)
    assert sorted(int(r["image"][0, 0, 0]) for r in rows) == [0, 10, 20, 30]
    assert all(r["path"].endswith(".png") for r in rows)


def test_read_binary_files(tmp_path):
    (tmp_path / "a.bin").write_bytes(b"\x01\x02")
    (tmp_path / "b.bin").write_bytes(b"\x03")
    rows = rdata.read_binary_files(str(tmp_path)).take(1000)
    assert sorted(r["bytes"] for r in rows) == [b"\x01\x02", b"\x03"]


def test_read_parquet_sharded_dir(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    for shard in range(3):
        table = pa.table({
            "x": np.arange(shard * 10, shard * 10 + 10),
        })
        pq.write_table(table, tmp_path / f"part-{shard}.parquet")
    ds = rdata.read_parquet(str(tmp_path))
    vals = sorted(int(r["x"]) for r in ds.take(1000))
    assert vals == list(range(30))
