"""Mesh / sharding-rule / collective tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from ray_tpu.parallel import (
    MeshSpec,
    P,
    build_mesh,
    default_rules,
    logical_to_spec,
    mesh_registry,
    override_rules,
    tree_specs,
    shard_tree,
)
from ray_tpu.parallel import collectives as col


@pytest.fixture
def mesh8():
    return build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))


def test_mesh_shape(mesh8):
    assert mesh8.shape["dp"] == 2
    assert mesh8.shape["fsdp"] == 2
    assert mesh8.shape["tp"] == 2
    assert mesh8.shape["sp"] == 1
    assert len(mesh8.devices.flatten()) == 8


def test_mesh_spec_validation():
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(dp=3))  # 3 != 8 devices


def test_mesh_spec_with_devices():
    spec = MeshSpec(tp=2).with_devices(8, prefer="fsdp")
    assert spec.fsdp == 4 and spec.tp == 2


def test_registry(mesh8):
    reg = mesh_registry()
    reg.clear()
    reg.register("train", mesh8)
    assert reg.get("train") is mesh8
    with pytest.raises(ValueError):
        reg.register("train", mesh8)
    reg.clear()


def test_logical_to_spec_basic():
    rules = default_rules()
    spec = logical_to_spec(("batch", "embed"), rules)
    assert spec == P(("dp", "fsdp"), "fsdp") or spec == P(("dp", "fsdp"), None)
    # fsdp already used by batch -> embed falls back to replicated
    assert spec[1] is None


def test_logical_to_spec_no_reuse():
    rules = default_rules()
    spec = logical_to_spec(("embed", "mlp"), rules)
    assert spec == P("fsdp", "tp")
    # vocab and mlp both want tp; second use must drop
    spec2 = logical_to_spec(("mlp", "vocab"), rules)
    assert spec2 == P("tp", None)


def test_override_rules():
    rules = override_rules(default_rules(), embed="tp")
    assert dict(rules)["embed"] == "tp"
    assert dict(rules)["mlp"] == "tp"


def test_shard_tree(mesh8):
    params = {
        "wq": jnp.zeros((16, 8)),
        "wo": jnp.zeros((8, 16)),
    }
    logical = {
        "wq": ("embed", "heads"),
        "wo": ("heads", "embed"),
    }
    sharded = shard_tree(params, logical, default_rules(), mesh8)
    assert sharded["wq"].sharding.spec == P("fsdp", "tp")
    # Each shard of wq is (16/2, 8/2)
    shard = sharded["wq"].addressable_shards[0]
    assert shard.data.shape == (8, 4)


def test_collective_allreduce(mesh8):
    group = col.CollectiveGroup(mesh8, axis="dp", name="t")
    x = jnp.arange(8.0)
    out = group.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2)


def test_collective_mean_max(mesh8):
    group = col.CollectiveGroup(mesh8, axis="tp", name="t2")
    x = jnp.ones((4,))
    np.testing.assert_allclose(np.asarray(group.allreduce(x, "mean")), np.ones(4))
    np.testing.assert_allclose(np.asarray(group.allreduce(x, "max")), np.ones(4))


def test_collective_allgather(mesh8):
    group = col.CollectiveGroup(mesh8, axis="dp")
    x = jnp.arange(4.0)
    out = group.allgather(x)
    assert out.shape == (2, 4)


def test_collective_barrier(mesh8):
    group = col.CollectiveGroup(mesh8, axis="fsdp")
    group.barrier()  # completes without deadlock


def test_group_manager(mesh8):
    g = col.init_collective_group(mesh8, "dp", "mygroup")
    assert col.get_group("mygroup") is g
    out = col.allreduce(jnp.ones(2), "mygroup")
    np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
    col.destroy_collective_group("mygroup")


def test_in_graph_collectives_under_shard_map(mesh8):
    """The hot-path mode: psum inside shard_map inside jit."""
    from functools import partial

    @jax.jit
    @partial(jax.shard_map, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
    def normalize(x):
        total = col.psum(jnp.sum(x), "dp")
        return x / total

    x = jnp.arange(8.0) + 1
    out = normalize(x)
    np.testing.assert_allclose(float(jnp.sum(out)), 1.0, rtol=1e-6)


def test_sharded_matmul_end_to_end(mesh8):
    """pjit-style sharded matmul: batch over dp/fsdp, weights over tp."""
    from jax.sharding import NamedSharding

    x = jax.device_put(
        np.random.default_rng(0).standard_normal((8, 16)).astype(np.float32),
        NamedSharding(mesh8, P(("dp", "fsdp"), None)),
    )
    w = jax.device_put(
        np.random.default_rng(1).standard_normal((16, 32)).astype(np.float32),
        NamedSharding(mesh8, P(None, "tp")),
    )
    out = jax.jit(lambda a, b: a @ b)(x, w)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x) @ np.asarray(w), rtol=1e-4
    )
    assert out.sharding.spec in (P(("dp", "fsdp"), "tp"), P(("dp", "fsdp"), None))
