"""Mesh / sharding-rule / collective tests on the virtual 8-device CPU mesh."""

import jax
from ray_tpu._jax_compat import shard_map as compat_shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from ray_tpu.parallel import (
    MeshSpec,
    P,
    build_mesh,
    default_rules,
    logical_to_spec,
    mesh_registry,
    override_rules,
    tree_specs,
    shard_tree,
)
from ray_tpu.parallel import collectives as col


@pytest.fixture
def mesh8():
    return build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))


def test_mesh_shape(mesh8):
    assert mesh8.shape["dp"] == 2
    assert mesh8.shape["fsdp"] == 2
    assert mesh8.shape["tp"] == 2
    assert mesh8.shape["sp"] == 1
    assert len(mesh8.devices.flatten()) == 8


def test_mesh_spec_validation():
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(dp=3))  # 3 != 8 devices


def test_mesh_spec_with_devices():
    spec = MeshSpec(tp=2).with_devices(8, prefer="fsdp")
    assert spec.fsdp == 4 and spec.tp == 2


def test_registry(mesh8):
    reg = mesh_registry()
    reg.clear()
    reg.register("train", mesh8)
    assert reg.get("train") is mesh8
    with pytest.raises(ValueError):
        reg.register("train", mesh8)
    reg.clear()


def test_logical_to_spec_basic():
    rules = default_rules()
    spec = logical_to_spec(("batch", "embed"), rules)
    assert spec == P(("dp", "fsdp"), "fsdp") or spec == P(("dp", "fsdp"), None)
    # fsdp already used by batch -> embed falls back to replicated
    assert spec[1] is None


def test_logical_to_spec_no_reuse():
    rules = default_rules()
    spec = logical_to_spec(("embed", "mlp"), rules)
    assert spec == P("fsdp", "tp")
    # vocab and mlp both want tp; second use must drop
    spec2 = logical_to_spec(("mlp", "vocab"), rules)
    assert spec2 == P("tp", None)


def test_override_rules():
    rules = override_rules(default_rules(), embed="tp")
    assert dict(rules)["embed"] == "tp"
    assert dict(rules)["mlp"] == "tp"


def test_shard_tree(mesh8):
    params = {
        "wq": jnp.zeros((16, 8)),
        "wo": jnp.zeros((8, 16)),
    }
    logical = {
        "wq": ("embed", "heads"),
        "wo": ("heads", "embed"),
    }
    sharded = shard_tree(params, logical, default_rules(), mesh8)
    assert sharded["wq"].sharding.spec == P("fsdp", "tp")
    # Each shard of wq is (16/2, 8/2)
    shard = sharded["wq"].addressable_shards[0]
    assert shard.data.shape == (8, 4)


def test_collective_allreduce(mesh8):
    group = col.CollectiveGroup(mesh8, axis="dp", name="t")
    x = jnp.arange(8.0)
    out = group.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2)


def test_collective_mean_max(mesh8):
    group = col.CollectiveGroup(mesh8, axis="tp", name="t2")
    x = jnp.ones((4,))
    np.testing.assert_allclose(np.asarray(group.allreduce(x, "mean")), np.ones(4))
    np.testing.assert_allclose(np.asarray(group.allreduce(x, "max")), np.ones(4))


def test_collective_allgather(mesh8):
    group = col.CollectiveGroup(mesh8, axis="dp")
    x = jnp.arange(4.0)
    out = group.allgather(x)
    assert out.shape == (2, 4)


def test_collective_barrier(mesh8):
    group = col.CollectiveGroup(mesh8, axis="fsdp")
    group.barrier()  # completes without deadlock


def test_group_manager(mesh8):
    g = col.init_collective_group(mesh8, "dp", "mygroup")
    assert col.get_group("mygroup") is g
    out = col.allreduce(jnp.ones(2), "mygroup")
    np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
    col.destroy_collective_group("mygroup")


def test_in_graph_collectives_under_shard_map(mesh8):
    """The hot-path mode: psum inside shard_map inside jit."""
    from functools import partial

    @jax.jit
    @partial(compat_shard_map, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
    def normalize(x):
        total = col.psum(jnp.sum(x), "dp")
        return x / total

    x = jnp.arange(8.0) + 1
    out = normalize(x)
    np.testing.assert_allclose(float(jnp.sum(out)), 1.0, rtol=1e-6)


def test_sharded_matmul_end_to_end(mesh8):
    """pjit-style sharded matmul: batch over dp/fsdp, weights over tp."""
    from jax.sharding import NamedSharding

    x = jax.device_put(
        np.random.default_rng(0).standard_normal((8, 16)).astype(np.float32),
        NamedSharding(mesh8, P(("dp", "fsdp"), None)),
    )
    w = jax.device_put(
        np.random.default_rng(1).standard_normal((16, 32)).astype(np.float32),
        NamedSharding(mesh8, P(None, "tp")),
    )
    out = jax.jit(lambda a, b: a @ b)(x, w)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x) @ np.asarray(w), rtol=1e-4
    )
    assert out.sharding.spec in (P(("dp", "fsdp"), "tp"), P(("dp", "fsdp"), None))


# --------------------------- regression tests for eager-collective semantics


def test_allgather_of_group_sharded_input(mesh8):
    """allgather over an input sharded on the group axis must return the
    stacked shards, not per-member duplicated copies."""
    from jax.sharding import NamedSharding

    g = col.CollectiveGroup(mesh8, axis="dp", name="ag_sharded")
    x = jax.device_put(
        jnp.arange(8.0), NamedSharding(mesh8, PartitionSpec("dp"))
    )
    out = g.allgather(x)
    # row i == shard i of the input (the stacked-shards contract)
    assert out.shape == (2, 4)
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(4.0))
    np.testing.assert_allclose(np.asarray(out[1]), np.arange(4.0) + 4)


def test_reducescatter_rejects_group_axis_in_spec(mesh8):
    from jax.sharding import NamedSharding

    g = col.CollectiveGroup(mesh8, axis="tp", name="rs_bad")
    y = jax.device_put(
        jnp.ones((4, 8)), NamedSharding(mesh8, PartitionSpec(None, "tp"))
    )
    with pytest.raises(ValueError, match="must not already be sharded"):
        g.reducescatter(y)


def test_reducescatter_basic(mesh8):
    g = col.CollectiveGroup(mesh8, axis="dp", name="rs_ok")
    x = jnp.ones((4, 8))
    out = g.reducescatter(x)
    assert out.shape == (4, 8)
    # every member contributed ones, summed over dp (size 2)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones((4, 8)))


def test_eager_collectives_hit_jit_cache(mesh8):
    g = col.CollectiveGroup(mesh8, axis="dp", name="cachecheck")
    x = jnp.ones((8,))
    g.allreduce(x)
    assert len(g._jitted) == 1
    g.allreduce(x)
    g.allreduce(2 * x)
    assert len(g._jitted) == 1  # same (kind, op, spec) key -> one program
    g.allreduce(x, op="max")
    assert len(g._jitted) == 2


def test_broadcast_from_root(mesh8):
    from jax.sharding import NamedSharding

    g = col.CollectiveGroup(mesh8, axis="dp", name="bcast2")
    # replicated input: broadcast is identity-shaped
    x = jnp.arange(4.0)
    out = g.broadcast(x, root=0)
    assert out.shape == (4,)
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0))


# ------------------------- quantized collectives & explicit dp sync drills


def test_quantize_int8_block_roundtrip_error_bound():
    x = jnp.asarray(np.random.default_rng(3).standard_normal((4, 512)), jnp.float32)
    q, s = col.quantize_int8_block(x, block=128)
    assert q.dtype == jnp.int8 and s.shape == (4, 4)
    deq = col.dequantize_int8_block(q, s)
    # per-block error bounded by half a quantization step
    err = np.abs(np.asarray(x) - np.asarray(deq))
    bound = np.repeat(np.asarray(s), 128, axis=1) * 0.5 + 1e-7
    assert (err <= bound).all()
    # zero blocks survive exactly
    z = jnp.zeros((1, 128))
    qz, sz = col.quantize_int8_block(z, block=128)
    np.testing.assert_array_equal(np.asarray(col.dequantize_int8_block(qz, sz)), 0.0)


def _dp8_mesh():
    return build_mesh(MeshSpec(dp=8))


def test_quantized_psum_rows_consistent_and_close():
    from functools import partial

    mesh = _dp8_mesh()
    n, k = 8, 1024
    x = np.random.default_rng(0).standard_normal((n, n, k)).astype(np.float32)
    exact = x.sum(axis=0)

    @jax.jit
    @partial(compat_shard_map, mesh=mesh, in_specs=P("dp"),
             out_specs=(P("dp"), P("dp")), check_vma=False)
    def qar(rows):
        red, err = col.quantized_psum_rows(rows[0], "dp", block=128)
        return red[None], err[None]

    red, err = qar(jnp.asarray(x))
    red, err = np.asarray(red), np.asarray(err)
    # every member reconstructs the SAME reduced tensor (consistency is
    # what keeps replicated optimizer states bit-identical across dp)
    for m in range(1, n):
        np.testing.assert_array_equal(red[0], red[m])
    rel = np.abs(red[0] - exact).max() / np.abs(exact).max()
    assert rel < 0.02, rel
    # error feedback closes the books: reduced + all members' residuals
    # equals the exact f32 sum (this identity is why EF converges)
    np.testing.assert_allclose(red[0] + err.sum(axis=0), exact, atol=1e-4)


def test_quantized_psum_scatter_rows_close_to_exact():
    from functools import partial

    mesh = _dp8_mesh()
    n, k = 8, 512
    x = np.random.default_rng(1).standard_normal((n, n, k)).astype(np.float32)
    exact = x.sum(axis=0)

    @jax.jit
    @partial(compat_shard_map, mesh=mesh, in_specs=P("dp"),
             out_specs=(P("dp"), P("dp")), check_vma=False)
    def qrs(rows):
        own, err = col.quantized_psum_scatter_rows(rows[0], "dp", block=128)
        return own[None], err[None]

    own, err = qrs(jnp.asarray(x))
    rel = np.abs(np.asarray(own) - exact).max() / np.abs(exact).max()
    assert rel < 0.02, rel
    np.testing.assert_allclose(
        np.asarray(own) + np.asarray(err).sum(axis=0), exact, atol=1e-4
    )


def test_dp_sync_bytes_accounting():
    p = 1_000_000
    full = col.dp_sync_bytes(p, 8, mode="f32")
    quant = col.dp_sync_bytes(p, 8, mode="int8", block=512)
    shard_quant = col.dp_sync_bytes(p, 8, mode="int8", shard_update=True, block=512)
    assert col.dp_sync_bytes(p, 1) == 0
    # int8 wire is ~3.9x cheaper than f32 on the grad stages
    assert full / quant > 3.5
    # sharded update pays int8 reduce-scatter + f32 param gather
    assert quant < shard_quant < full


def test_sharded_update_matches_replicated_exactly():
    """The dp_shard_update machinery (rows layout -> shard slice -> adam on
    the shard -> all-gather) must reproduce the replicated optimizer update
    BIT-FOR-BIT at f32 given the same synced gradients — adam is
    elementwise, so any divergence is a layout bug."""
    import optax
    from functools import partial
    from ray_tpu.train.lm import _from_rows, _to_rows

    mesh = _dp8_mesh()
    n, block = 8, 64
    params = {
        "w": jnp.asarray(np.random.default_rng(5).standard_normal((37, 11)), jnp.float32),
        "b": jnp.asarray(np.random.default_rng(6).standard_normal(13), jnp.float32),
    }
    grads = jax.tree.map(
        lambda p: jnp.asarray(
            np.random.default_rng(7).standard_normal(p.shape), jnp.float32
        ),
        params,
    )
    opt = optax.adam(3e-3)

    # replicated reference: three plain updates (jitted, same as the
    # sharded program — eager numerics fuse differently at the ulp level)
    @jax.jit
    def ref_step(p, g, st):
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st

    state = opt.init(params)
    p_ref = params
    for _ in range(3):
        p_ref, state = ref_step(p_ref, grads, state)

    # sharded: opt state lives in rows layout, each member updates its row
    rows_template = jax.tree.map(lambda p: _to_rows(p, n, block), params)
    opt_rows = opt.init(rows_template)
    opt_specs = jax.tree.map(
        lambda x: P("dp") if getattr(x, "ndim", 0) >= 1 else P(), opt_rows
    )

    @jax.jit
    @partial(
        compat_shard_map, mesh=mesh,
        in_specs=(P(), P(), opt_specs),
        out_specs=(P(), opt_specs),
        check_vma=False,
    )
    def sharded_step(p, g, opt_local):
        my = jax.lax.axis_index("dp")
        g_shard = jax.tree.map(lambda x: _to_rows(x, n, block)[my], g)
        p_shard = jax.tree.map(lambda x: _to_rows(x, n, block)[my], p)
        opt_sq = jax.tree.map(
            lambda x: x[0] if getattr(x, "ndim", 0) >= 2 and x.shape[0] == 1 else x,
            opt_local,
        )
        upd, new_opt = opt.update(g_shard, opt_sq, p_shard)
        new_shard = optax.apply_updates(p_shard, upd)
        rows = jax.tree.map(
            lambda s_: jax.lax.all_gather(s_, "dp", axis=0, tiled=False),
            new_shard,
        )
        new_p = jax.tree.map(lambda r, x: _from_rows(r, x), rows, p)
        new_opt = jax.tree.map(
            lambda x: x[None] if getattr(x, "ndim", 0) >= 1 else x, new_opt
        )
        return new_p, new_opt

    p_sh = params
    for _ in range(3):
        p_sh, opt_rows = sharded_step(p_sh, grads, opt_rows)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_sh)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_explicit_dp_step_variants_match_standard():
    """End-to-end make_train_step: the explicit shard_map dp paths (f32
    sharded update; int8 quantized all-reduce; both) track the standard
    XLA-partitioned step on a real model — f32 sharded is float-order-only
    off, int8 within quantization tolerance — and converge."""
    import optax
    from ray_tpu.models import get_config
    from ray_tpu.train import create_train_state, make_train_step

    config = get_config("gpt2-tiny")
    mesh = _dp8_mesh()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 33), 0, config.vocab_size)
    batch = {"tokens": tokens}

    def run(n_steps, **kw):
        opt = optax.adam(5e-3)
        state, sh = create_train_state(
            config, opt, jax.random.PRNGKey(0), mesh,
            dp_shard_update=kw.get("dp_shard_update", False),
            dp_error_feedback=kw.get("dp_allreduce_dtype") == "int8",
        )
        step = make_train_step(
            config, opt, mesh, state_shardings=sh, loss_chunk=0, **kw
        )
        losses = []
        for _ in range(n_steps):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    s_std, l_std = run(8, dp_allreduce_dtype="f32", dp_shard_update=False)
    s_shard, l_shard = run(8, dp_shard_update=True)
    s_q, l_q = run(8, dp_allreduce_dtype="int8")

    # sharded f32: same math, different float association only
    for a, b in zip(jax.tree.leaves(s_std.params), jax.tree.leaves(s_shard.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-4)
    np.testing.assert_allclose(l_std, l_shard, rtol=1e-4)

    # int8 + error feedback: converges with the f32 run within tolerance
    assert l_std[-1] < l_std[0]  # the drill actually trains
    assert abs(l_q[-1] - l_std[-1]) < 0.05, (l_q, l_std)
    # error-feedback buffer is alive (non-zero residuals are being carried)
    ef_norm = sum(
        float(jnp.sum(jnp.abs(e))) for e in jax.tree.leaves(s_q.ef)
    )
    assert ef_norm > 0.0


def test_path_specs_search_semantics(mesh8):
    from ray_tpu.parallel.sharding import path_specs

    tree = {"decoder": {"wq": jnp.ones((4, 4)), "wq_norm": jnp.ones((4,))}}
    specs = path_specs(tree, [(r"wq_norm", PartitionSpec()), (r"wq", PartitionSpec("tp"))])
    assert specs["decoder"]["wq"] == PartitionSpec("tp")
    assert specs["decoder"]["wq_norm"] == PartitionSpec()
