"""Mesh / sharding-rule / collective tests on the virtual 8-device CPU mesh."""

import jax
from ray_tpu._jax_compat import shard_map as compat_shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from ray_tpu.parallel import (
    MeshSpec,
    P,
    build_mesh,
    default_rules,
    logical_to_spec,
    mesh_registry,
    override_rules,
    tree_specs,
    shard_tree,
)
from ray_tpu.parallel import collectives as col


@pytest.fixture
def mesh8():
    return build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))


def test_mesh_shape(mesh8):
    assert mesh8.shape["dp"] == 2
    assert mesh8.shape["fsdp"] == 2
    assert mesh8.shape["tp"] == 2
    assert mesh8.shape["sp"] == 1
    assert len(mesh8.devices.flatten()) == 8


def test_mesh_spec_validation():
    with pytest.raises(ValueError):
        build_mesh(MeshSpec(dp=3))  # 3 != 8 devices


def test_mesh_spec_with_devices():
    spec = MeshSpec(tp=2).with_devices(8, prefer="fsdp")
    assert spec.fsdp == 4 and spec.tp == 2


def test_registry(mesh8):
    reg = mesh_registry()
    reg.clear()
    reg.register("train", mesh8)
    assert reg.get("train") is mesh8
    with pytest.raises(ValueError):
        reg.register("train", mesh8)
    reg.clear()


def test_logical_to_spec_basic():
    rules = default_rules()
    spec = logical_to_spec(("batch", "embed"), rules)
    assert spec == P(("dp", "fsdp"), "fsdp") or spec == P(("dp", "fsdp"), None)
    # fsdp already used by batch -> embed falls back to replicated
    assert spec[1] is None


def test_logical_to_spec_no_reuse():
    rules = default_rules()
    spec = logical_to_spec(("embed", "mlp"), rules)
    assert spec == P("fsdp", "tp")
    # vocab and mlp both want tp; second use must drop
    spec2 = logical_to_spec(("mlp", "vocab"), rules)
    assert spec2 == P("tp", None)


def test_override_rules():
    rules = override_rules(default_rules(), embed="tp")
    assert dict(rules)["embed"] == "tp"
    assert dict(rules)["mlp"] == "tp"


def test_shard_tree(mesh8):
    params = {
        "wq": jnp.zeros((16, 8)),
        "wo": jnp.zeros((8, 16)),
    }
    logical = {
        "wq": ("embed", "heads"),
        "wo": ("heads", "embed"),
    }
    sharded = shard_tree(params, logical, default_rules(), mesh8)
    assert sharded["wq"].sharding.spec == P("fsdp", "tp")
    # Each shard of wq is (16/2, 8/2)
    shard = sharded["wq"].addressable_shards[0]
    assert shard.data.shape == (8, 4)


def test_collective_allreduce(mesh8):
    group = col.CollectiveGroup(mesh8, axis="dp", name="t")
    x = jnp.arange(8.0)
    out = group.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2)


def test_collective_mean_max(mesh8):
    group = col.CollectiveGroup(mesh8, axis="tp", name="t2")
    x = jnp.ones((4,))
    np.testing.assert_allclose(np.asarray(group.allreduce(x, "mean")), np.ones(4))
    np.testing.assert_allclose(np.asarray(group.allreduce(x, "max")), np.ones(4))


def test_collective_allgather(mesh8):
    group = col.CollectiveGroup(mesh8, axis="dp")
    x = jnp.arange(4.0)
    out = group.allgather(x)
    assert out.shape == (2, 4)


def test_collective_barrier(mesh8):
    group = col.CollectiveGroup(mesh8, axis="fsdp")
    group.barrier()  # completes without deadlock


def test_group_manager(mesh8):
    g = col.init_collective_group(mesh8, "dp", "mygroup")
    assert col.get_group("mygroup") is g
    out = col.allreduce(jnp.ones(2), "mygroup")
    np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
    col.destroy_collective_group("mygroup")


def test_in_graph_collectives_under_shard_map(mesh8):
    """The hot-path mode: psum inside shard_map inside jit."""
    from functools import partial

    @jax.jit
    @partial(compat_shard_map, mesh=mesh8, in_specs=P("dp"), out_specs=P("dp"))
    def normalize(x):
        total = col.psum(jnp.sum(x), "dp")
        return x / total

    x = jnp.arange(8.0) + 1
    out = normalize(x)
    np.testing.assert_allclose(float(jnp.sum(out)), 1.0, rtol=1e-6)


def test_sharded_matmul_end_to_end(mesh8):
    """pjit-style sharded matmul: batch over dp/fsdp, weights over tp."""
    from jax.sharding import NamedSharding

    x = jax.device_put(
        np.random.default_rng(0).standard_normal((8, 16)).astype(np.float32),
        NamedSharding(mesh8, P(("dp", "fsdp"), None)),
    )
    w = jax.device_put(
        np.random.default_rng(1).standard_normal((16, 32)).astype(np.float32),
        NamedSharding(mesh8, P(None, "tp")),
    )
    out = jax.jit(lambda a, b: a @ b)(x, w)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x) @ np.asarray(w), rtol=1e-4
    )
    assert out.sharding.spec in (P(("dp", "fsdp"), "tp"), P(("dp", "fsdp"), None))


# --------------------------- regression tests for eager-collective semantics


def test_allgather_of_group_sharded_input(mesh8):
    """allgather over an input sharded on the group axis must return the
    stacked shards, not per-member duplicated copies."""
    from jax.sharding import NamedSharding

    g = col.CollectiveGroup(mesh8, axis="dp", name="ag_sharded")
    x = jax.device_put(
        jnp.arange(8.0), NamedSharding(mesh8, PartitionSpec("dp"))
    )
    out = g.allgather(x)
    # row i == shard i of the input (the stacked-shards contract)
    assert out.shape == (2, 4)
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(4.0))
    np.testing.assert_allclose(np.asarray(out[1]), np.arange(4.0) + 4)


def test_reducescatter_rejects_group_axis_in_spec(mesh8):
    from jax.sharding import NamedSharding

    g = col.CollectiveGroup(mesh8, axis="tp", name="rs_bad")
    y = jax.device_put(
        jnp.ones((4, 8)), NamedSharding(mesh8, PartitionSpec(None, "tp"))
    )
    with pytest.raises(ValueError, match="must not already be sharded"):
        g.reducescatter(y)


def test_reducescatter_basic(mesh8):
    g = col.CollectiveGroup(mesh8, axis="dp", name="rs_ok")
    x = jnp.ones((4, 8))
    out = g.reducescatter(x)
    assert out.shape == (4, 8)
    # every member contributed ones, summed over dp (size 2)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones((4, 8)))


def test_eager_collectives_hit_jit_cache(mesh8):
    g = col.CollectiveGroup(mesh8, axis="dp", name="cachecheck")
    x = jnp.ones((8,))
    g.allreduce(x)
    assert len(g._jitted) == 1
    g.allreduce(x)
    g.allreduce(2 * x)
    assert len(g._jitted) == 1  # same (kind, op, spec) key -> one program
    g.allreduce(x, op="max")
    assert len(g._jitted) == 2


def test_broadcast_from_root(mesh8):
    from jax.sharding import NamedSharding

    g = col.CollectiveGroup(mesh8, axis="dp", name="bcast2")
    # replicated input: broadcast is identity-shaped
    x = jnp.arange(4.0)
    out = g.broadcast(x, root=0)
    assert out.shape == (4,)
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0))


def test_path_specs_search_semantics(mesh8):
    from ray_tpu.parallel.sharding import path_specs

    tree = {"decoder": {"wq": jnp.ones((4, 4)), "wq_norm": jnp.ones((4,))}}
    specs = path_specs(tree, [(r"wq_norm", PartitionSpec()), (r"wq", PartitionSpec("tp"))])
    assert specs["decoder"]["wq"] == PartitionSpec("tp")
    assert specs["decoder"]["wq_norm"] == PartitionSpec()
