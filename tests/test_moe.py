"""MoE: routing invariants, forward/backward, expert-parallel sharded run."""

import jax
from ray_tpu._jax_compat import set_mesh as compat_set_mesh
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.moe import (
    MoEConfig,
    forward,
    init_params,
    load_balancing_loss,
    logical_axes,
    moe_loss,
    moe_tiny,
    topk_dispatch,
)
from ray_tpu.parallel import MeshSpec, build_mesh, default_rules, shard_tree


@pytest.fixture
def model():
    config = moe_tiny()
    params = init_params(config, jax.random.PRNGKey(0))
    return config, params


def test_topk_dispatch_invariants():
    probs = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4)), -1)
    dispatch, combine = topk_dispatch(probs, top_k=2, capacity=16)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # ample capacity: every token dispatched exactly top_k times
    np.testing.assert_allclose(d.sum((2, 3)), 2.0)
    # each (expert, slot) holds at most one token
    assert (d.sum((0, 1)) <= 1.0 + 1e-6).all() or True  # per batch row:
    assert (d.sum(1) <= 1.0 + 1e-6).all()
    # combine weights per token sum to 1 (renormalized top-k)
    np.testing.assert_allclose(c.sum((2, 3)), 1.0, atol=1e-5)


def test_topk_dispatch_capacity_drops():
    # all tokens want expert 0 → only `capacity` survive
    probs = jnp.zeros((1, 8, 4)).at[:, :, 0].set(1.0)
    dispatch, _ = topk_dispatch(probs, top_k=1, capacity=3)
    assert float(dispatch.sum()) == 3.0


def test_forward_shapes_and_aux(model):
    config, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, config.vocab_size)
    logits, aux = forward(params, tokens, config)
    assert logits.shape == (2, 16, config.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # balanced-ish routing at init → aux near 1.0 (its minimum is 1)
    assert 0.9 < float(aux) / config.n_layers < 2.5


def test_param_axes_match(model):
    config, params = model
    axes = logical_axes(config)
    flat_p = {tuple(str(k) for k, _ in []) for _ in []}
    p_paths = {
        tuple(str(k) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    a_paths = {
        tuple(str(k) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(
            axes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
    }
    assert p_paths == a_paths


def test_grad_flows_including_router(model):
    config, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, config.vocab_size)
    grads = jax.grad(lambda p: moe_loss(p, tokens, config)[0])(params)
    router_norm = float(jnp.linalg.norm(grads["blocks"]["router"]))
    expert_norm = float(jnp.linalg.norm(grads["blocks"]["we_up"]))
    assert np.isfinite(router_norm) and router_norm > 0
    assert np.isfinite(expert_norm) and expert_norm > 0


def test_expert_parallel_sharded_matches_replicated(model):
    config, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, config.vocab_size)
    expected, aux_e = forward(params, tokens, config)

    mesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2))
    sharded = shard_tree(params, logical_axes(config), default_rules(), mesh)
    assert sharded["blocks"]["we_up"].sharding.spec[1] == "ep"
    fwd = jax.jit(lambda p, t: forward(p, t, config))
    with compat_set_mesh(mesh):
        out, aux = fwd(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(float(aux), float(aux_e), rtol=1e-5)


def test_moe_training_reduces_loss(model):
    config, params = model
    import optax

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 17), 0, config.vocab_size)

    @jax.jit
    def step(params, opt_state):
        (loss, _), grads = jax.value_and_grad(
            lambda p: moe_loss(p, tokens, config), has_aux=True
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
