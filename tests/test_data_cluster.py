"""Data pipelines ON the cluster (round-4 verdict #9): read + map tasks
spill to agent nodes — blocks flow as refs pulled where consumed, and a
multi-node cluster actually adds data throughput.

Reference model: task_pool_map_operator.py dispatches cluster-wide.
"""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_map_batches_spans_agents(cluster):
    """A 12-block map pipeline on a 3-node cluster: results are exact
    and the map tasks executed on >= 2 distinct agent processes."""
    fd, log = tempfile.mkstemp(prefix="ray_tpu_datapids_")
    os.close(fd)

    def square_and_log(block, _log=log):
        import os as _os

        fdl = _os.open(_log, _os.O_WRONLY | _os.O_APPEND)
        try:
            _os.write(fdl, f"{_os.getpid()}\n".encode())
        finally:
            _os.close(fdl)
        # hold briefly so blocks overlap across nodes
        time.sleep(0.15)
        return {"item": block["item"] ** 2}

    ctx = rdata.DataContext.get_current()
    old_prefetch = ctx.prefetch_blocks
    ctx.prefetch_blocks = 8  # enough in-flight tasks to need both agents
    try:
        ds = rdata.range(1200, num_blocks=12).map_batches(square_and_log)
        total = sum(int(r) for r in ds.take(2000))
    finally:
        ctx.prefetch_blocks = old_prefetch
    assert total == sum(i * i for i in range(1200))

    with open(log) as f:
        pids = {int(line) for line in f if line.strip()}
    agent_pids = {
        rec["pid"] for rec in cluster.runtime.cluster.nodes()
        if not rec["is_head"]
    }
    assert len(pids & agent_pids) >= 2, (
        f"map tasks used {pids}, agents are {agent_pids}"
    )
    os.unlink(log)


def test_actor_pool_udf_on_cluster(cluster):
    """Stateful ActorPoolStrategy udfs place across the cluster too
    (actors spill when the head cannot host the whole pool)."""

    class AddOffset:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, block):
            return {"item": block["item"] + self.offset}

    ds = rdata.range(100, num_blocks=4).map_batches(
        AddOffset, compute=rdata.ActorPoolStrategy(size=2),
        fn_constructor_args=(1000,),
    )
    vals = sorted(int(r) for r in ds.take(200))
    assert vals == [i + 1000 for i in range(100)]


@pytest.fixture
def pinned_cluster():
    """Like `cluster`, but with the remote-inline cutoff forced tiny so
    task results STAY in their producer node's store (the default 512 KiB
    cutoff would ship these small test blocks back inline and leave
    nothing to lose when a node dies)."""
    sysconf = {"node_heartbeat_s": 0.2, "remote_inline_max_bytes": 64}
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, **sysconf},
        }
    )
    c.add_node(num_cpus=2, system_config=sysconf)
    c.add_node(num_cpus=2, system_config=sysconf)
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_kill_node_mid_ingest_exactly_once(pinned_cluster):
    """PR 12 chaos drill: blocks live in their producer node's store;
    SIGKILL that node before the consumer fetches and the lost blocks
    must re-execute via lineage — the consumer still sees every row
    exactly once, and the store counts the reconstructions."""
    cluster = pinned_cluster
    ctx = rdata.DataContext.get_current()
    old_prefetch = ctx.prefetch_blocks
    ctx.prefetch_blocks = 16  # submit the whole 12-block plan up front
    try:
        ds = rdata.range(600, num_blocks=12).map_batches(
            lambda b: {"item": b["item"] * 2}
        )
        refs = list(ds.iter_block_refs())
        assert len(refs) == 12
        # wait for every block to seal WITHOUT fetching any — the values
        # must still be sitting in the agents' stores when one dies
        ready, pending = ray_tpu.wait(refs, num_returns=12, timeout=120)
        assert not pending
        victim = cluster._nodes[0]
        cluster.remove_node(victim, allow_graceful=False)
        deadline = time.monotonic() + 30
        while (len(cluster.runtime.scheduler.nodes()) > 2
               and time.monotonic() < deadline):
            time.sleep(0.1)

        blocks = ray_tpu.get(refs, timeout=120)
        rows = sorted(int(r) for b in blocks for r in b["item"])
        assert rows == [i * 2 for i in range(600)], "rows not exactly-once"
        assert cluster.runtime.object_store.stats["reconstructions"] > 0, (
            "killing a producer node should have forced lineage re-execution"
        )
    finally:
        ctx.prefetch_blocks = old_prefetch


def test_cluster_ingest_locality_routing(cluster):
    """Map tasks carry a locality hint for the node holding their input
    block; on an idle cluster most should land there (soft preference —
    feasibility still wins, so the bar here is majority, not 100%).
    The rate is statistical and a just-started cluster's first round
    can lose it to discovery races, so the majority bar gets three
    independent pipelines to clear."""
    stats = None
    best = 0.0
    for _ in range(3):
        ds = rdata.range(400, num_blocks=8).map_batches(
            lambda b: {"item": b["item"] + 1}
        )
        total = sum(int(r) for r in ds.take(500))
        assert total == sum(i + 1 for i in range(400))
        stats = ds.stats()
        assert stats["locality_total"] > 0
        best = max(best, stats["locality_hit_rate"])
        if best >= 0.5:
            break
    assert best >= 0.5, stats
