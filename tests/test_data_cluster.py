"""Data pipelines ON the cluster (round-4 verdict #9): read + map tasks
spill to agent nodes — blocks flow as refs pulled where consumed, and a
multi-node cluster actually adds data throughput.

Reference model: task_pool_map_operator.py dispatches cluster-wide.
"""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_map_batches_spans_agents(cluster):
    """A 12-block map pipeline on a 3-node cluster: results are exact
    and the map tasks executed on >= 2 distinct agent processes."""
    fd, log = tempfile.mkstemp(prefix="ray_tpu_datapids_")
    os.close(fd)

    def square_and_log(block, _log=log):
        import os as _os

        fdl = _os.open(_log, _os.O_WRONLY | _os.O_APPEND)
        try:
            _os.write(fdl, f"{_os.getpid()}\n".encode())
        finally:
            _os.close(fdl)
        # hold briefly so blocks overlap across nodes
        time.sleep(0.15)
        return {"item": block["item"] ** 2}

    ctx = rdata.DataContext.get_current()
    old_prefetch = ctx.prefetch_blocks
    ctx.prefetch_blocks = 8  # enough in-flight tasks to need both agents
    try:
        ds = rdata.range(1200, num_blocks=12).map_batches(square_and_log)
        total = sum(int(r) for r in ds.take(2000))
    finally:
        ctx.prefetch_blocks = old_prefetch
    assert total == sum(i * i for i in range(1200))

    with open(log) as f:
        pids = {int(line) for line in f if line.strip()}
    agent_pids = {
        rec["pid"] for rec in cluster.runtime.cluster.nodes()
        if not rec["is_head"]
    }
    assert len(pids & agent_pids) >= 2, (
        f"map tasks used {pids}, agents are {agent_pids}"
    )
    os.unlink(log)


def test_actor_pool_udf_on_cluster(cluster):
    """Stateful ActorPoolStrategy udfs place across the cluster too
    (actors spill when the head cannot host the whole pool)."""

    class AddOffset:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, block):
            return {"item": block["item"] + self.offset}

    ds = rdata.range(100, num_blocks=4).map_batches(
        AddOffset, compute=rdata.ActorPoolStrategy(size=2),
        fn_constructor_args=(1000,),
    )
    vals = sorted(int(r) for r in ds.take(200))
    assert vals == [i + 1000 for i in range(100)]
