"""Data layer: plans, streaming execution, splits, LM packing, train feed."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()


def test_range_count_take():
    ds = rd.range(100, num_blocks=7)
    assert ds.count() == 100
    assert ds.take(5) == [0, 1, 2, 3, 4]


def test_map_and_filter():
    ds = rd.range(20).map(lambda x: x * 2).filter(lambda x: x % 8 == 0)
    rows = sorted(ds.take(100))
    assert rows == [0, 8, 16, 24, 32]


def test_map_batches_columnar():
    ds = rd.from_numpy({"x": np.arange(32)}, num_blocks=4)
    out = ds.map_batches(lambda b: {"y": b["x"] + 1})
    assert sorted(np.concatenate([b["y"] for b in out.iter_blocks()]).tolist()) == list(
        range(1, 33)
    )


def test_iter_batches_across_block_boundaries():
    ds = rd.range(25, num_blocks=4)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [rd.block_num_rows(b) for b in batches]
    assert sizes == [10, 10, 5]
    batches = list(ds.iter_batches(batch_size=10, drop_last=True))
    assert [rd.block_num_rows(b) for b in batches] == [10, 10]


def test_limit_short_circuits():
    ds = rd.range(1000, num_blocks=100).limit(15)
    assert ds.count() == 15


def test_shuffle_preserves_multiset():
    ds = rd.range(64, num_blocks=8).random_shuffle(seed=0)
    rows = [r for r in ds.iter_rows()]
    assert sorted(rows) == list(range(64))
    assert rows != list(range(64))  # actually permuted


def test_repartition():
    ds = rd.range(30, num_blocks=3).repartition(5)
    blocks = list(ds.iter_blocks())
    assert len(blocks) == 5
    assert sum(rd.block_num_rows(b) for b in blocks) == 30


def test_from_items_dict_rows():
    rows = [{"a": i, "b": i * i} for i in range(10)]
    ds = rd.from_items(rows, num_blocks=3)
    out = ds.take(10)
    assert out[3] == {"a": 3, "b": 9}


def test_read_text(tmp_path):
    p1 = tmp_path / "a.txt"
    p1.write_text("hello\nworld\n")
    p2 = tmp_path / "b.txt"
    p2.write_text("foo\n")
    ds = rd.read_text(str(tmp_path / "*.txt"))
    texts = sorted(row["text"] for row in ds.take(10))
    assert texts == ["foo", "hello", "world"]


def test_read_npy(tmp_path):
    np.save(tmp_path / "s0.npy", np.arange(10, dtype=np.int32))
    np.save(tmp_path / "s1.npy", np.arange(10, 20, dtype=np.int32))
    ds = rd.read_npy(str(tmp_path / "*.npy"))
    total = np.concatenate([b["tokens"] for b in ds.iter_blocks()])
    assert sorted(total.tolist()) == list(range(20))


def test_streaming_split_round_robin():
    ds = rd.range(40, num_blocks=8)
    it0, it1 = ds.streaming_split(2)
    rows0 = [r for r in it0.iter_rows()]
    rows1 = [r for r in it1.iter_rows()]
    assert sorted(rows0 + rows1) == list(range(40))
    assert rows0 and rows1


def test_streaming_split_concurrent_consumers():
    import threading

    ds = rd.range(100, num_blocks=10)
    its = ds.streaming_split(4)
    results = [[] for _ in range(4)]

    def consume(i):
        results[i] = [r for r in its[i].iter_rows()]

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(sum(results, [])) == list(range(100))


def test_pack_tokens_windows():
    blocks = iter([{"tokens": np.arange(100, dtype=np.int32)}])
    batches = list(rd.pack_tokens(blocks, seq_len=9, batch_size=2))
    # 100 tokens → 10 windows of 10 → 5 batches of 2
    assert len(batches) == 5
    assert batches[0]["tokens"].shape == (2, 10)
    np.testing.assert_array_equal(batches[0]["tokens"][0], np.arange(10))
    np.testing.assert_array_equal(batches[0]["tokens"][1], np.arange(10, 20))


def test_pack_tokens_ragged_docs():
    col = np.empty(2, dtype=object)
    col[0] = list(range(7))
    col[1] = list(range(7, 12))
    blocks = iter([{"tokens": col}])
    batches = list(rd.pack_tokens(blocks, seq_len=3, batch_size=1))
    assert len(batches) == 3  # 12 tokens → 3 windows of 4
    np.testing.assert_array_equal(batches[0]["tokens"][0], [0, 1, 2, 3])


def test_lm_pipeline_feeds_trainer():
    """End-to-end: dataset → pack → LMTrainer step (tiny, CPU mesh)."""
    import jax

    from ray_tpu.models import get_config
    from ray_tpu.parallel import MeshSpec
    from ray_tpu.train import LMTrainer

    config = get_config("gpt2-tiny")
    stream = rd.from_numpy(
        {"tokens": np.random.default_rng(0).integers(0, 255, 3000).astype(np.int32)},
        num_blocks=4,
    )
    trainer = LMTrainer(
        config, mesh_spec=MeshSpec(dp=2, fsdp=2, tp=2), learning_rate=1e-3, total_steps=5
    )
    batches = rd.lm_batch_iterator(stream, seq_len=16, batch_size=8)
    metrics = trainer.train(batches, num_steps=5, report_every=5)
    assert metrics["step"] == 5
    assert np.isfinite(metrics["loss"])


def test_read_csv_and_json(tmp_path, runtime):
    csv_path = tmp_path / "t.csv"
    csv_path.write_text("a,b,name\n1,2.5,x\n3,4.5,y\n")
    ds = ray_tpu.data.read_csv(str(csv_path))
    rows = ds.take(10)
    assert rows[0]["a"] == 1 and rows[1]["b"] == 4.5 and rows[0]["name"] == "x"

    jl = tmp_path / "t.jsonl"
    jl.write_text('{"x": 1, "y": "p"}\n{"x": 2, "y": "q"}\n')
    ds = ray_tpu.data.read_json(str(jl))
    assert ds.count() == 2
    assert ds.map(lambda r: r["x"] * 10).take(2) == [10, 20]


def test_actor_pool_map_batches(runtime):
    from ray_tpu.data import ActorPoolStrategy

    class AddOffset:
        """Stateful udf: __init__ once per actor."""

        def __init__(self, offset):
            self.offset = offset
            self.inits = 1

        def __call__(self, block):
            return {"item": block["item"] + self.offset}

    ds = ray_tpu.data.range(64, num_blocks=8).map_batches(
        AddOffset, compute=ActorPoolStrategy(size=2),
        fn_constructor_args=(1000,),
    )
    out = sorted(ds.iter_rows())
    assert out == list(__import__("builtins").range(1000, 1064))

    with pytest.raises(ValueError, match="ActorPoolStrategy"):
        ray_tpu.data.range(4).map_batches(AddOffset)


def test_from_generator_streams_blocks(runtime):
    import numpy as np

    def gen():
        for i in __import__("builtins").range(5):
            yield {"v": np.arange(4) + i * 4}  # unknown cardinality upstream

    ds = ray_tpu.data.from_generator(gen)
    assert ds.count() == 20
    # transforms compose on top of the streaming read
    doubled = ray_tpu.data.from_generator(gen).map_batches(
        lambda b: {"v": b["v"] * 2}
    )
    vals = sorted(r["v"] for r in doubled.iter_rows())
    assert vals == [v * 2 for v in __import__("builtins").range(20)]


# ---------------------------------------------------------- process executor


def test_map_batches_process_executor_runs_off_driver(runtime):
    """executor="process": stateless block maps run in pooled OS worker
    processes (GIL-free), not the driver (VERDICT r3 weak #1)."""
    import os

    import ray_tpu

    driver_pid = os.getpid()

    def tag_pid(block):
        import os as _os

        return {"pid": np.full(len(block["x"]), _os.getpid(), dtype=np.int64)}

    ds = ray_tpu.data.from_numpy({"x": np.arange(64)}, num_blocks=4)
    out = ds.map_batches(tag_pid, executor="process")
    pids = set(np.concatenate([b["pid"] for b in out.iter_blocks()]).tolist())
    assert driver_pid not in pids, "process-executor map ran on the driver"


def test_actor_pool_process_executor(runtime):
    """ActorPoolStrategy(executor="process"): stateful udf actors live in
    their own OS processes; __init__ state persists across blocks."""
    import os

    import ray_tpu

    class Tagger:
        def __init__(self, base):
            self.base = base
            self.pid = os.getpid()

        def __call__(self, block):
            n = len(block["x"])
            return {
                "y": block["x"] + self.base,
                "pid": np.full(n, self.pid, dtype=np.int64),
            }

    ds = ray_tpu.data.from_numpy({"x": np.arange(32)}, num_blocks=4)
    blocks = list(
        ds.map_batches(
            Tagger,
            compute=ray_tpu.data.ActorPoolStrategy(size=2, executor="process"),
            fn_constructor_args=(100,),
        ).iter_blocks()
    )
    ys = sorted(np.concatenate([b["y"] for b in blocks]).tolist())
    assert ys == list(range(100, 132))
    pids = set(np.concatenate([b["pid"] for b in blocks]).tolist())
    assert os.getpid() not in pids


@pytest.mark.skipif((os.cpu_count() or 1) < 4,
                    reason="multi-core speedup needs >= 4 cores")
def test_process_executor_beats_threads_on_cpu_bound_udf(runtime):
    """On a multi-core host, a CPU-bound pure-Python udf over 4 process
    workers must beat the GIL-bound thread path by >= 2x."""
    import time

    import ray_tpu

    def burn(block):
        acc = 0
        for _ in range(3_000_000):
            acc += 1
        return {"x": block["x"] + (acc >= 0)}

    ds = ray_tpu.data.from_numpy({"x": np.arange(8)}, num_blocks=8)

    t0 = time.perf_counter()
    list(ds.map_batches(burn).iter_blocks())
    t_thread = time.perf_counter() - t0

    t0 = time.perf_counter()
    list(ds.map_batches(burn, executor="process").iter_blocks())
    t_proc = time.perf_counter() - t0
    assert t_proc * 2 < t_thread, (t_proc, t_thread)
