"""Model tests: GPT-2/Llama forward, decode-cache equivalence, sharded run."""

import jax
from ray_tpu._jax_compat import set_mesh as compat_set_mesh
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (
    TransformerConfig,
    count_params,
    decode_step,
    forward,
    get_config,
    init_cache,
    init_params,
    logical_axes,
    prefill,
)
from ray_tpu.parallel import MeshSpec, build_mesh, default_rules, shard_tree


@pytest.fixture(params=["gpt2-tiny", "llama-tiny"])
def model(request):
    config = get_config(request.param)
    params = init_params(config, jax.random.PRNGKey(0))
    return config, params


def test_forward_shapes(model):
    config, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, config.vocab_size)
    logits = forward(params, tokens, config)
    assert logits.shape == (2, 16, config.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_param_tree_matches_axes_tree(model):
    config, params = model
    axes = logical_axes(config)
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_a = jax.tree_util.tree_flatten_with_path(axes, is_leaf=lambda x: isinstance(x, tuple))[0]
    paths_p = {tuple(str(k) for k in path) for path, _ in flat_p}
    paths_a = {tuple(str(k) for k in path) for path, _ in flat_a}
    assert paths_p == paths_a
    # every axes tuple has same rank as the parameter
    amap = {tuple(str(k) for k in path): a for path, a in flat_a}
    for path, leaf in flat_p:
        assert len(amap[tuple(str(k) for k in path)]) == leaf.ndim, path


def test_causality(model):
    """Changing a future token must not affect past logits."""
    config, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, config.vocab_size)
    logits1 = forward(params, tokens, config)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % config.vocab_size)
    logits2 = forward(params, tokens2, config)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
    )
    assert not np.allclose(np.asarray(logits1[0, 10:]), np.asarray(logits2[0, 10:]))


def test_decode_matches_forward(model):
    """Step-by-step decode with cache == full forward, per position."""
    config, params = model
    b, s = 2, 12
    tokens = jax.random.randint(jax.random.PRNGKey(3), (b, s), 0, config.vocab_size)
    full = forward(params, tokens, config)

    cache = init_cache(config, b, max_seq=config.max_seq)
    step = jax.jit(lambda p, c, t, pos: decode_step(p, c, t, pos, config))
    for t in range(s):
        positions = jnp.full((b,), t, dtype=jnp.int32)
        logits, cache = step(params, cache, tokens[:, t], positions)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, t]), atol=2e-4, rtol=2e-4
        )


def test_prefill_then_decode(model):
    """prefill(prompt) + decode_step == forward over the whole sequence."""
    config, params = model
    b, prompt_len = 2, 8
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (b, prompt_len + 1), 0, config.vocab_size
    )
    full = forward(params, tokens, config)

    cache = init_cache(config, b)
    lengths = jnp.full((b,), prompt_len, dtype=jnp.int32)
    last_logits, cache = prefill(params, tokens[:, :prompt_len], lengths, cache, config)
    np.testing.assert_allclose(
        np.asarray(last_logits), np.asarray(full[:, prompt_len - 1]), atol=2e-4, rtol=2e-4
    )
    # one decode step after the prompt
    logits, cache = decode_step(
        params, cache, tokens[:, prompt_len], jnp.full((b,), prompt_len, jnp.int32), config
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, prompt_len]), atol=2e-4, rtol=2e-4
    )


def test_ragged_decode_positions():
    """Examples at different positions decode correctly in one batch."""
    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 10), 0, config.vocab_size)
    full = forward(params, tokens, config)

    # example 0 is at position 5, example 1 at position 3
    cache = init_cache(config, 2)
    for t in range(6):
        pos = jnp.array([t, min(t, 3)], dtype=jnp.int32)
        cur = jnp.stack([tokens[0, t], tokens[1, min(t, 3)]])
        logits, cache = decode_step(params, cache, cur, pos, config)
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(full[0, 5]), atol=2e-4, rtol=2e-4)


def test_sharded_forward_on_mesh():
    """FSDP+TP-sharded params produce the same logits as replicated."""
    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, config.vocab_size)
    expected = forward(params, tokens, config)

    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    sharded = shard_tree(params, logical_axes(config), default_rules(), mesh)
    fwd = jax.jit(lambda p, t: forward(p, t, config))
    with compat_set_mesh(mesh):
        out = fwd(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-4, rtol=1e-4)


def test_param_counts_gpt2_small():
    config = get_config("gpt2-small")
    params = init_params(config, jax.random.PRNGKey(0))
    n = count_params(params)
    assert 120e6 < n < 130e6, n  # ~124M


def test_grad_flows(model):
    config, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, config.vocab_size)

    def loss(p):
        logits = forward(p, tokens, config)
        from ray_tpu.ops import cross_entropy_loss

        l, _ = cross_entropy_loss(logits[:, :-1], tokens[:, 1:])
        return l

    g = jax.grad(loss)(params)
    norms = [float(jnp.linalg.norm(x)) for x in jax.tree.leaves(g)]
    assert all(np.isfinite(norms))
    assert sum(norms) > 0


def test_fused_qkv_and_unroll_match_baseline():
    """The perf knobs are numerically inert."""
    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, config.vocab_size)
    base = forward(params, tokens, config)
    fused = forward(params, tokens, config.replace(fused_qkv=True))
    unrolled = forward(params, tokens, config.replace(scan_unroll=4))
    np.testing.assert_allclose(np.asarray(base), np.asarray(fused), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(base), np.asarray(unrolled), atol=1e-6)
