"""Serve: streaming responses, deployment composition, model multiplexing
(reference: Serve streaming over ASGI, deployment graphs,
serve/multiplex.py + LoRA multiplexing)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.streaming import ObjectRefGenerator


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


# ---------------------------------------------------------------- streaming


@serve.deployment
class Tokens:
    def generate(self, payload):
        for i in range(payload["n"]):
            yield {"token": i * 10}

    def __call__(self, payload):
        return {"ok": True}


def test_handle_streaming():
    handle = serve.run(Tokens.bind())
    stream = handle.options(stream=True).generate.remote({"n": 4})
    assert isinstance(stream, ObjectRefGenerator)
    items = [ray_tpu.get(r) for r in stream]
    assert items == [{"token": 0}, {"token": 10}, {"token": 20}, {"token": 30}]


def test_http_streaming_chunked():
    serve.run(Tokens.bind())
    port = serve.start_http()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/Tokens/generate?stream=1",
        data=json.dumps({"n": 3}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers["Content-Type"] == "application/jsonl"
        lines = [json.loads(l) for l in resp.read().decode().splitlines() if l]
    assert lines == [{"result": {"token": i * 10}} for i in range(3)]


# -------------------------------------------------------------- composition


@serve.deployment
class Preprocess:
    def __call__(self, payload):
        return {"text": payload["text"].strip().lower()}


@serve.deployment
class Classify:
    def __init__(self, preproc):
        self.preproc = preproc  # a DeploymentHandle (deployed child app)

    def __call__(self, payload):
        clean = ray_tpu.get(self.preproc.remote(payload))
        return {"label": "greeting" if "hello" in clean["text"] else "other"}


def test_deployment_composition():
    handle = serve.run(Classify.bind(Preprocess.bind()))
    out = ray_tpu.get(handle.remote({"text": "  HELLO world "}))
    assert out == {"label": "greeting"}
    # the child deployed as its own deployment with its own replicas
    st = serve.status()
    assert "Preprocess" in st and "Classify" in st
    assert st["Preprocess"]["live_replicas"] >= 1


# ------------------------------------------------------------- multiplexing


@serve.deployment
class Adapters:
    def __init__(self):
        self.loads = []

    @serve.multiplexed(max_num_models_per_replica=2)
    def get_model(self, model_id):
        self.loads.append(model_id)
        return {"id": model_id, "weights": f"w-{model_id}"}

    def __call__(self, payload):
        model_id = serve.get_multiplexed_model_id()
        model = self.get_model(model_id)
        return {"model": model["id"], "loads": list(self.loads)}


def test_multiplexing_lru_and_affinity():
    handle = serve.run(Adapters.bind())
    h_a = handle.options(multiplexed_model_id="m-a")
    h_b = handle.options(multiplexed_model_id="m-b")

    out1 = ray_tpu.get(h_a.remote({}))
    assert out1["model"] == "m-a" and out1["loads"] == ["m-a"]
    # same model again: cached, no second load
    out2 = ray_tpu.get(h_a.remote({}))
    assert out2["loads"] == ["m-a"]
    # second model loads alongside (cap 2)
    out3 = ray_tpu.get(h_b.remote({}))
    assert out3["loads"] == ["m-a", "m-b"]
    # third model evicts the LRU (m-a); re-requesting m-a reloads
    ray_tpu.get(handle.options(multiplexed_model_id="m-c").remote({}))
    out5 = ray_tpu.get(h_a.remote({}))
    assert out5["loads"].count("m-a") == 2


def test_multiplex_affinity_prefers_loaded_replica():
    dep = Adapters.options(name="Adapters2", num_replicas=3)
    handle = serve.run(dep.bind())
    h = handle.options(multiplexed_model_id="hot")
    outs = [ray_tpu.get(h.remote({})) for _ in range(8)]
    # affinity keeps the hot model on at most 2 replicas: total loads of
    # "hot" across the fleet stay <= 2 despite 8 requests over 3 replicas
    all_loads = outs[-1]["loads"]
    assert sum(1 for x in all_loads if x == "hot") <= 1  # per-replica view
    total_loads = {tuple(o["loads"]) for o in outs}
    assert len(total_loads) <= 2  # at most 2 distinct replicas ever served it


def test_llm_token_streaming_over_http():
    """End-to-end serving story: paged engine -> serve streaming handle ->
    chunked HTTP, one JSON line per token (OpenAI stream=true shape)."""
    from ray_tpu.serve.llm import PagedConfig, PagedEngineConfig, build_llm_app

    app = build_llm_app("llama-tiny", name="llm-stream", max_slots=2, paged=True)
    # shrink the page pool for the tiny model
    app.init_args = (
        app.init_args[0], app.init_args[1],
        PagedEngineConfig(max_slots=2, paged=PagedConfig(
            page_size=8, num_pages=32, max_pages_per_slot=8, chunk_pages=2)),
    )
    handle = serve.run(app)
    # via the streaming handle
    stream = handle.options(stream=True).stream_generate.remote(
        {"prompt_tokens": [5, 6, 7], "max_tokens": 4}
    )
    items = [ray_tpu.get(r) for r in stream]
    assert len(items) == 5  # 4 tokens + final usage record
    assert all("token" in it for it in items[:4])
    assert items[-1]["done"] and items[-1]["usage"]["completion_tokens"] == 4
    # via HTTP chunked
    port = serve.start_http()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/llm-stream/stream_generate?stream=1",
        data=json.dumps({"prompt_tokens": [5, 6, 7], "max_tokens": 3}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        lines = [json.loads(l) for l in resp.read().decode().splitlines() if l]
    assert len(lines) == 4
    assert lines[-1]["result"]["done"] is True
