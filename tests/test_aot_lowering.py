"""AOT lowering of FLAGSHIP-scale sharded train steps.

BASELINE.md's target configs include Llama-3-8B FSDP on a slice. 8B
params cannot materialize on the CI host, but the whole point of the
jit/pjit design is that sharding correctness is decided at TRACE time:
jax.eval_shape builds the abstract state and `step.lower(...)` runs the
full SPMD partitioner over the real 8B shapes on the 8-device mesh —
without allocating a byte of parameter memory. This is the same gate the
driver's dryrun applies to the tiny model, at flagship scale.
"""

import dataclasses

import jax
import pytest


@pytest.fixture(autouse=True)
def _shardy_partitioner():
    """These tests assert axis-name sharding markers (sdy.mesh, {"tp"},
    {"fsdp"}) in the lowered HLO. The pinned jax defaults to the GSPMD
    partitioner whose text form carries device-id shardings instead;
    Shardy is available behind a flag — enable it for this module and
    restore the default after (lowering-only: nothing executes here)."""
    old = jax.config.jax_use_shardy_partitioner
    jax.config.update("jax_use_shardy_partitioner", True)
    yield
    jax.config.update("jax_use_shardy_partitioner", old)

from ray_tpu.models import get_config
from ray_tpu.models.transformer import logical_axes
from ray_tpu.parallel import MeshSpec, build_mesh, default_rules
from ray_tpu.parallel.sharding import tree_specs
from ray_tpu.train import default_optimizer, make_train_step
from ray_tpu.train.lm import TrainState, _sharding_tree, infer_state_specs, init_params


def _abstract_state_and_shardings(config, opt, mesh):
    rules = default_rules()
    param_specs = tree_specs(logical_axes(config), rules)

    def build(key):
        params = init_params(config, key)
        return TrainState(
            step=jax.numpy.zeros((), jax.numpy.int32),
            params=params,
            opt_state=opt.init(params),
            rng=jax.random.fold_in(key, 1),
        )

    abstract = jax.eval_shape(build, jax.random.PRNGKey(0))
    spec_tree = infer_state_specs(abstract, param_specs)
    spec_tree = dataclasses.replace(spec_tree, params=param_specs)
    shardings = _sharding_tree(spec_tree, mesh)
    abs_state = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract, shardings,
    )
    return abs_state, shardings


@pytest.mark.parametrize("spec", [MeshSpec(fsdp=4, tp=2), MeshSpec(dp=2, fsdp=4)])
def test_llama3_8b_train_step_lowers_sharded(spec):
    config = get_config("llama3-8b")
    assert config.n_layers == 32 and config.d_model == 4096  # the real 8B
    mesh = build_mesh(spec)
    opt = default_optimizer(3e-4, total_steps=100)
    abs_state, shardings = _abstract_state_and_shardings(config, opt, mesh)
    step = make_train_step(config, opt, mesh, state_shardings=shardings)

    from jax.sharding import NamedSharding, PartitionSpec

    batch_sharding = NamedSharding(
        mesh, PartitionSpec(("dp", "fsdp"), None)
    )
    abs_batch = {
        "tokens": jax.ShapeDtypeStruct(
            (8, 2048 + 1), jax.numpy.int32, sharding=batch_sharding
        )
    }
    lowered = step.lower(abs_state, abs_batch)
    hlo = lowered.as_text()
    # the SPMD program targets all 8 partitions with a Shardy mesh naming
    # our axes, and the big params arrive SHARDED on the fsdp axis (not
    # replicated) with donated (aliased) outputs for in-place updates
    assert "mhlo.num_partitions = 8" in hlo
    assert "sdy.mesh" in hlo and '"fsdp"=' in hlo
    assert '{"fsdp"}' in hlo, "no parameter is fsdp-sharded in the HLO"
    assert "tf.aliasing_output" in hlo, "state donation missing"
    # params land sharded, not replicated: the fsdp axis must appear in
    # the sharding of at least one large parameter
    flat_sh = jax.tree.leaves(
        jax.tree.map(lambda s: s.spec, shardings.params)
    )
    assert any("fsdp" in str(s) for s in flat_sh)


def test_llama3_8b_state_bytes_scale_with_shards():
    """Per-device parameter bytes must shrink by the fsdp factor — the
    ZeRO-3 property, checked arithmetically from the abstract shapes."""
    config = get_config("llama3-8b")
    mesh = build_mesh(MeshSpec(fsdp=8))
    opt = default_optimizer(3e-4, total_steps=100)
    abs_state, shardings = _abstract_state_and_shardings(config, opt, mesh)
    total = 0
    sharded = 0
    for leaf, sh in zip(
        jax.tree.leaves(abs_state.params), jax.tree.leaves(shardings.params)
    ):
        nbytes = leaf.size * leaf.dtype.itemsize
        total += nbytes
        import numpy as np

        shard_shape = sh.shard_shape(leaf.shape)
        sharded += int(np.prod(shard_shape)) * leaf.dtype.itemsize
    assert total > 25e9  # ~8B fp32 params
    # per-device slice must be well under 1/4 of the total (fsdp=8)
    assert sharded < total / 4, (sharded, total)


def test_llama3_8b_tp_serving_lowers_sharded():
    """VERDICT r3 #2: the paged serving engine's decode block — the exact
    program PagedLLMEngine dispatches — must partition at Llama-3-8B
    shapes over a tp=8 mesh: params Megatron-split, the KV page pool
    sharded on the kv-head axis, token I/O replicated."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from ray_tpu.serve.llm.paged import PagedConfig, init_paged_cache
    from ray_tpu.serve.llm.paged_engine import (
        _sample_plain,
        build_decode_block,
        serving_shardings,
    )

    config = get_config("llama3-8b")
    assert config.kv_heads == 8 and config.n_heads == 32
    mesh = build_mesh(MeshSpec(tp=8))
    pc = PagedConfig(page_size=64, num_pages=512, max_pages_per_slot=32,
                     chunk_pages=4)
    param_sh, cache_sh, rep = serving_shardings(config, mesh)

    abs_params = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        jax.eval_shape(lambda k: init_params(config, k), jax.random.PRNGKey(0)),
        param_sh,
    )
    abs_cache = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        jax.eval_shape(lambda: init_paged_cache(config, pc)),
        cache_sh,
    )
    B, K = 8, 16
    decode = build_decode_block(config, pc.page_size, K, _sample_plain,
                                use_kernel=False)
    jitted = jax.jit(
        decode, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh, rep, rep, rep, rep, rep),
        out_shardings=(rep, rep, cache_sh),
    )
    i32 = jax.numpy.int32
    abs_in = (
        jax.ShapeDtypeStruct((B, pc.max_pages_per_slot), i32, sharding=rep),
        jax.ShapeDtypeStruct((B,), i32, sharding=rep),
        jax.ShapeDtypeStruct((B,), i32, sharding=rep),
        jax.eval_shape(lambda: jax.random.PRNGKey(0)),
        jax.ShapeDtypeStruct((B,), jax.numpy.float32, sharding=rep),
    )
    hlo = jitted.lower(abs_params, abs_cache, *abs_in).as_text()
    assert "mhlo.num_partitions = 8" in hlo
    assert '{"tp"}' in hlo, "nothing is tp-sharded in the serving HLO"
    # the vLLM property that matters on HBM: per-device KV pool bytes
    # shrink by the tp factor (pool sharded on kv heads, not replicated)
    k_leaf = jax.eval_shape(lambda: init_paged_cache(config, pc))["k"]
    shard_shape = cache_sh["k"].shard_shape(k_leaf.shape)
    assert np.prod(shard_shape) * 8 == np.prod(k_leaf.shape) * 1, (
        shard_shape, k_leaf.shape
    )
    # and at least one attention projection lands tp-sharded
    flat = jax.tree.leaves(jax.tree.map(lambda s: str(s.spec), param_sh))
    assert any("'tp'" in s for s in flat)


def test_mixtral_8x7b_moe_lowers_expert_parallel():
    """BASELINE config 3: the REAL Mixtral 8x7B shapes (8 experts, 32
    layers, d_ff 14336) lower through the partitioner on a dp2 x ep4
    mesh with expert-stacked weights sharded on the ep axis."""
    from ray_tpu.models import moe

    config = moe.mixtral_8x7b()
    assert config.n_experts == 8 and config.d_ff == 14336
    mesh = build_mesh(MeshSpec(dp=2, ep=4))
    rules = default_rules()
    param_specs = tree_specs(moe.logical_axes(config), rules)
    abstract = jax.eval_shape(
        lambda key: moe.init_params(config, key), jax.random.PRNGKey(0)
    )
    from jax.sharding import NamedSharding, PartitionSpec

    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs
    )
    abs_params = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract, shardings,
    )
    batch_sharding = NamedSharding(mesh, PartitionSpec(("dp",), None))
    abs_tokens = jax.ShapeDtypeStruct(
        (8, 1024 + 1), jax.numpy.int32, sharding=batch_sharding
    )

    loss_fn = jax.jit(lambda p, t: moe.moe_loss(p, t, config)[0])
    hlo = loss_fn.lower(abs_params, abs_tokens).as_text()
    assert "mhlo.num_partitions = 8" in hlo
    assert '{"ep"}' in hlo, "no expert-stacked weight is ep-sharded"
    # the expert-parallel property: per-device expert bytes shrink by ep
    import numpy as np

    expert_leaf = abstract["blocks"]["we_up"]
    sh = shardings["blocks"]["we_up"]
    shard = np.prod(sh.shard_shape(expert_leaf.shape))
    assert shard * 4 <= np.prod(expert_leaf.shape), "experts not sharded"
