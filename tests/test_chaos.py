"""Chaos injection: failures surface as task errors; retries recover."""

import pytest

import ray_tpu
from ray_tpu.core import chaos


@pytest.fixture(autouse=True)
def rt():
    runtime = ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield runtime
    chaos.clear_chaos()
    ray_tpu.shutdown()


def test_injected_failure_surfaces_as_task_error():
    chaos.set_chaos(failure_prob=1.0, name_filter="victim")

    @ray_tpu.remote(name="victim")
    def victim():
        return 1

    with pytest.raises(ray_tpu.TaskError, match="chaos"):
        ray_tpu.get(victim.remote())


def test_name_filter_spares_other_tasks():
    chaos.set_chaos(failure_prob=1.0, name_filter="victim")

    @ray_tpu.remote(name="innocent")
    def innocent():
        return 42

    assert ray_tpu.get(innocent.remote()) == 42


def test_retries_recover_from_bounded_chaos():
    # exactly 2 injections, then clean: max_retries=3 must succeed
    chaos.set_chaos(failure_prob=1.0, max_injections=2, name_filter="flaky")

    @ray_tpu.remote(name="flaky", max_retries=3, retry_exceptions=True)
    def flaky():
        return "survived"

    assert ray_tpu.get(flaky.remote()) == "survived"
    assert chaos.num_injected() == 2


def test_chaos_env_parsing(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_CHAOS", "failure_prob=0.5,delay_s=0.01,max_injections=3,name_filter=x"
    )
    chaos.load_from_env()
    cfg = chaos._state.config
    assert cfg.failure_prob == 0.5
    assert cfg.delay_s == 0.01
    assert cfg.max_injections == 3
    assert cfg.name_filter == "x"
    assert cfg.kill_node is False


def test_kill_node_env_parsing(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_CHAOS", "kill_node=1,name_filter=boom,max_injections=1"
    )
    chaos.load_from_env()
    cfg = chaos._state.config
    assert cfg.kill_node is True
    assert cfg.name_filter == "boom"
    assert cfg.max_injections == 1


def test_kill_node_hard_exits_matching_task(monkeypatch):
    """kill_node escalates an injection to process death (os._exit):
    filtered by task name, bounded by max_injections."""
    exits = []
    monkeypatch.setattr(chaos.os, "_exit", lambda code: exits.append(code))
    chaos.set_chaos(kill_node=True, name_filter="die", max_injections=1)
    chaos.maybe_inject("innocent")
    assert exits == []
    chaos.maybe_inject("die-here")
    assert exits == [137]
    assert chaos.num_injected() == 1
    chaos.maybe_inject("die-here")  # budget exhausted: no second kill
    assert exits == [137]


def test_chaos_under_training_controller_restart():
    """End-to-end: chaos kills the train fn; the failure policy restarts."""
    from ray_tpu.train import FailureConfig, RunConfig, ScalingConfig, Trainer

    chaos.set_chaos(failure_prob=1.0, max_injections=1, name_filter="TrainWorker.run")

    def loop(config):
        from ray_tpu import train

        train.report({"ok": 1})
        return "done"

    trainer = Trainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(failure=FailureConfig(max_failures=2)),
        train_loop_config={},
    )
    result = trainer.fit()
    assert result.status.value == "FINISHED"
