"""Channels + compiled actor DAGs (reference: python/ray/dag/
compiled_dag_node.py:805, experimental/channel/shared_memory_channel.py:151,
experimental_mutable_object_manager.h:44)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.experimental import (
    Channel,
    ChannelClosedError,
    ChannelReader,
    InputNode,
    MultiOutputNode,
)


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


# ------------------------------------------------------------------ channels


def test_channel_version_semantics():
    ch = Channel(num_readers=1)
    r = ChannelReader(ch)
    ch.write("a")
    assert r.read() == "a"
    ch.write("b")
    assert r.read() == "b"
    with pytest.raises(TimeoutError):
        r.read(timeout=0.05)  # no new version


def test_channel_backpressure_blocks_writer():
    ch = Channel(num_readers=1)
    r = ChannelReader(ch)
    ch.write(1)
    with pytest.raises(TimeoutError):
        ch.write(2, timeout=0.05)  # reader has not consumed v1
    assert r.read() == 1
    ch.write(2)
    assert r.read() == 2


def test_channel_multi_reader_each_sees_each_version():
    ch = Channel(num_readers=2)
    r1, r2 = ChannelReader(ch), ChannelReader(ch)
    ch.write("x")
    assert r1.read() == "x"
    with pytest.raises(TimeoutError):
        ch.write("y", timeout=0.05)  # r2 still owes a read
    assert r2.read() == "x"
    ch.write("y")
    assert (r1.read(), r2.read()) == ("y", "y")


def test_channel_close_unblocks():
    ch = Channel(num_readers=1)
    r = ChannelReader(ch)
    errs = []

    def blocked_read():
        try:
            r.read(timeout=10)
        except ChannelClosedError as e:
            errs.append(e)

    t = threading.Thread(target=blocked_read)
    t.start()
    time.sleep(0.05)
    ch.close()
    t.join(timeout=5)
    assert errs and not t.is_alive()
    with pytest.raises(ChannelClosedError):
        ch.write(1)


# ---------------------------------------------------------------------- DAGs


@ray_tpu.remote
class Adder:
    def __init__(self, inc):
        self.inc = inc
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.inc

    def add2(self, x, y):
        return x + y

    def boom(self, x):
        raise ValueError(f"bad input {x}")

    def ncalls(self):
        return self.calls


def test_linear_dag_pipeline():
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        x = a.add.bind(inp)
        y = b.add.bind(x)
    dag = y.experimental_compile()
    try:
        futs = [dag.execute(i) for i in range(5)]
        assert [f.get(timeout=30) for f in futs] == [11 + i for i in range(5)]
    finally:
        dag.teardown()


def test_dag_reuses_actors_without_task_submission():
    a = Adder.remote(5)
    with InputNode() as inp:
        out = a.add.bind(inp)
    dag = out.experimental_compile()
    try:
        for i in range(20):
            assert dag.execute(i).get(timeout=30) == i + 5
    finally:
        dag.teardown()
    # the loop ran inside ONE __ray_apply__ call; method state persisted
    assert ray_tpu.get(a.ncalls.remote()) == 20


def test_dag_fan_out_and_multi_output():
    a = Adder.remote(1)
    b = Adder.remote(2)
    c = Adder.remote(3)
    with InputNode() as inp:
        x = a.add.bind(inp)       # consumed by two downstream stages
        y = b.add.bind(x)
        z = c.add.bind(x)
    dag = MultiOutputNode([y, z]).experimental_compile()
    try:
        assert dag.execute(10).get(timeout=30) == [13, 14]
        assert dag.execute(0).get(timeout=30) == [3, 4]
    finally:
        dag.teardown()


def test_dag_join_two_upstreams():
    a = Adder.remote(1)
    b = Adder.remote(2)
    j = Adder.remote(0)
    with InputNode() as inp:
        x = a.add.bind(inp)
        y = b.add.bind(inp)
        out = j.add2.bind(x, y)
    dag = out.experimental_compile()
    try:
        assert dag.execute(10).get(timeout=30) == 11 + 12
    finally:
        dag.teardown()


def test_dag_const_args():
    a = Adder.remote(0)
    with InputNode() as inp:
        out = a.add2.bind(inp, 100)
    dag = out.experimental_compile()
    try:
        assert dag.execute(1).get(timeout=30) == 101
    finally:
        dag.teardown()


def test_dag_error_propagates_to_future():
    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        x = a.boom.bind(inp)
        y = b.add.bind(x)
    dag = y.experimental_compile()
    try:
        with pytest.raises(ValueError, match="bad input 7"):
            dag.execute(7).get(timeout=30)
        # the pipeline survives an error and keeps serving
        with pytest.raises(ValueError, match="bad input 8"):
            dag.execute(8).get(timeout=30)
    finally:
        dag.teardown()


def test_dag_teardown_releases_actor():
    a = Adder.remote(1)
    with InputNode() as inp:
        out = a.add.bind(inp)
    dag = out.experimental_compile()
    assert dag.execute(1).get(timeout=30) == 2
    dag.teardown()
    # the actor's executor thread is free again for normal calls
    assert ray_tpu.get(a.ncalls.remote(), timeout=30) == 1
    with pytest.raises(RuntimeError, match="torn down"):
        dag.execute(2)


def test_dag_rejects_same_actor_twice_and_multiple_inputs():
    a = Adder.remote(1)
    with InputNode() as inp:
        x = a.add.bind(inp)
        y = a.add.bind(x)  # same actor bound twice
    with pytest.raises(ValueError, match="more than one DAG node"):
        y.experimental_compile()

    b, c = Adder.remote(1), Adder.remote(2)
    i1, i2 = InputNode(), InputNode()
    with pytest.raises(ValueError, match="multiple InputNodes"):
        MultiOutputNode([b.add.bind(i1), c.add.bind(i2)]).experimental_compile()


# ------------------------------------------------------- cross-process DAGs


def test_compiled_dag_with_process_actors(runtime):
    """A compiled DAG spanning PROCESS actors: edges ride shared-memory
    channels (shm_channel.ShmChannel), the pipeline stays ordered, and
    teardown reaps the loops (VERDICT r3 missing #6: cross-process
    compiled-graph channels)."""

    @ray_tpu.remote(executor="process")
    class Doubler:
        def apply(self, x):
            return x * 2

    @ray_tpu.remote(executor="process")
    class AddTen:
        def apply(self, x):
            return x + 10

    a = Doubler.remote()
    b = AddTen.remote()
    with InputNode() as inp:
        mid = a.apply.bind(inp)
        out = b.apply.bind(mid)
    dag = out.experimental_compile()
    assert dag._use_shm
    try:
        futs = [dag.execute(i, timeout=30) for i in range(5)]
        assert [f.get(timeout=60) for f in futs] == [10, 12, 14, 16, 18]
    finally:
        dag.teardown()


def test_compiled_dag_mixed_executors(runtime):
    """Thread + process actors in ONE graph: every edge switches to shm."""
    import os

    @ray_tpu.remote(executor="process")
    class Remote:
        def pid_and(self, x):
            return (os.getpid(), x + 1)

    @ray_tpu.remote
    class Local:
        def unwrap(self, t):
            return t

    r = Remote.remote()
    l = Local.remote()
    with InputNode() as inp:
        out = l.unwrap.bind(r.pid_and.bind(inp))
    dag = out.experimental_compile()
    try:
        pid, v = dag.execute(41, timeout=30).get(timeout=60)
        assert v == 42 and pid != os.getpid()
    finally:
        dag.teardown()


def test_compiled_dag_process_actor_error_flows(runtime):
    @ray_tpu.remote(executor="process")
    class Boom:
        def apply(self, x):
            if x == 2:
                raise ValueError("dag kaboom")
            return x

    a = Boom.remote()
    with InputNode() as inp:
        out = a.apply.bind(inp)
    dag = out.experimental_compile()
    try:
        assert dag.execute(1, timeout=30).get(timeout=60) == 1
        with pytest.raises(ValueError, match="dag kaboom"):
            dag.execute(2, timeout=30).get(timeout=60)
        assert dag.execute(3, timeout=30).get(timeout=60) == 3
    finally:
        dag.teardown()
