"""Failure detection + OOM policy + GCS persistence (reference:
gcs_health_check_manager.h:45, memory_monitor.h:52,
worker_killing_policy*.h, gcs_table_storage.h:275)."""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.core.config import cfg
from ray_tpu.core.health import HealthCheckManager, MemoryMonitor


@pytest.fixture(autouse=True)
def _clean_cfg():
    yield
    cfg.reset()
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


# ------------------------------------------------------------- health checks


def test_health_threshold_and_single_callback():
    hc = HealthCheckManager(period_s=999, failure_threshold=3)
    alive = {"v": True}
    deaths = []
    hc.register("t1", lambda: alive["v"], deaths.append)
    assert hc.check_once() == []
    alive["v"] = False
    assert hc.check_once() == []  # 1 failure
    assert hc.check_once() == []  # 2 failures
    assert hc.check_once() == ["t1"]  # threshold
    assert hc.check_once() == []  # fired once; target unregistered
    assert deaths == ["t1"]


def test_health_recovery_resets_counter():
    hc = HealthCheckManager(period_s=999, failure_threshold=2)
    alive = {"v": False}
    deaths = []
    hc.register("t", lambda: alive["v"], deaths.append)
    hc.check_once()
    alive["v"] = True
    hc.check_once()  # recovers -> counter resets
    alive["v"] = False
    hc.check_once()
    assert deaths == []  # only 1 consecutive failure again
    hc.check_once()
    assert deaths == ["t"]


def test_killed_process_actor_detected_and_restarted_without_calls():
    """The core failure-detection story: a process actor's OS process is
    killed while idle; the health checker notices and restarts it."""
    ray_tpu.init(
        num_cpus=4,
        detect_accelerators=False,
        _system_config={"health_check_period_s": 0.05},
    )

    @ray_tpu.remote(executor="process", max_restarts=2)
    class Svc:
        def __init__(self):
            self.hits = 0

        def hit(self):
            self.hits += 1
            return self.hits

    svc = Svc.remote()
    assert ray_tpu.get(svc.hit.remote(), timeout=60) == 1
    pid = ray_tpu.get(svc.__ray_pid__.remote(), timeout=60)
    os.kill(pid, signal.SIGKILL)
    # NO method call in flight: only the health checker can notice.
    deadline = time.monotonic() + 30
    new_pid = None
    while time.monotonic() < deadline:
        try:
            new_pid = ray_tpu.get(svc.__ray_pid__.remote(), timeout=30)
            if new_pid != pid:
                break
        except Exception:
            time.sleep(0.1)
    assert new_pid is not None and new_pid != pid
    # restarted instance: fresh state
    assert ray_tpu.get(svc.hit.remote(), timeout=60) == 1


# ------------------------------------------------------------ memory monitor


def test_memory_monitor_kills_newest_busy_worker():
    ray_tpu.init(num_cpus=4, detect_accelerators=False)

    usage = {"v": 0.0}
    mon = MemoryMonitor(
        threshold=0.9, interval_s=0, policy="retriable_fifo",
        usage_fn=lambda: usage["v"],
    )
    assert mon.check_once() is False  # below threshold

    @ray_tpu.remote(executor="process", max_retries=1, retry_exceptions=True)
    def slowly(x):
        import time as _t

        _t.sleep(1.0)
        return x * 2

    ref = slowly.remote(21)
    # wait for the worker to actually be busy
    from ray_tpu.core.worker_pool import get_worker_pool

    pool = get_worker_pool()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with pool._lock:
            if pool._busy:
                break
        time.sleep(0.02)
    usage["v"] = 0.97
    assert mon.check_once() is True  # a worker was killed
    assert mon.stats["kills"] == 1
    # the task was retriable: it re-runs and still completes
    assert ray_tpu.get(ref, timeout=120) == 42


def test_memory_monitor_bad_policy_rejected():
    with pytest.raises(ValueError, match="unknown oom policy"):
        MemoryMonitor(0.9, 1.0, policy="lottery")


# -------------------------------------------------------------- persistence


def test_gcs_snapshot_restore_roundtrip(tmp_path):
    snap = str(tmp_path / "gcs.snap")
    ray_tpu.init(
        num_cpus=2,
        detect_accelerators=False,
        _system_config={"gcs_snapshot_path": snap, "gcs_snapshot_interval_s": 0.1},
    )
    rt = ray_tpu.api._runtime()
    rt.gcs.kv.put("model_path", "/ckpt/step_100", namespace="train")
    rt.gcs.kv.put("cluster_name", "alpha")

    @ray_tpu.remote
    class Reg:
        def ping(self):
            return "ok"

    h = Reg.options(name="registrar").remote()
    assert ray_tpu.get(h.ping.remote()) == "ok"

    from ray_tpu.jobs import default_job_manager

    mgr = default_job_manager()
    jid = mgr.submit("python -c 'print(1)'", job_id="snap-job")
    mgr.wait(jid, timeout=30)
    ray_tpu.shutdown()  # final snapshot on shutdown
    assert os.path.exists(snap)

    # fresh control plane restores the durable tables
    import ray_tpu.jobs as jobs_mod

    jobs_mod._default_manager = None  # simulate a new process's job manager
    cfg.reset()
    ray_tpu.init(
        num_cpus=2,
        detect_accelerators=False,
        _system_config={"gcs_snapshot_path": snap},
    )
    rt2 = ray_tpu.api._runtime()
    assert rt2.gcs.kv.get("model_path", namespace="train") == "/ckpt/step_100"
    assert rt2.gcs.kv.get("cluster_name") == "alpha"
    # the name is REMEMBERED (existed-before-restart), handle is gone
    assert "registrar" in rt2.gcs.list_named_actors()
    assert rt2.gcs.get_named_actor("registrar") is None

    # the placeholder must be reclaimable: re-creating the actor works
    @ray_tpu.remote
    class Reg2:
        def ping(self):
            return "back"

    h2 = Reg2.options(name="registrar").remote()
    assert ray_tpu.get(h2.ping.remote()) == "back"
    restored = default_job_manager().info("snap-job")
    assert restored.status.value == "SUCCEEDED"
    assert restored.entrypoint == "python -c 'print(1)'"
