"""ActorPool + distributed Queue (reference ray.util.actor_pool/queue)."""

import threading

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Empty, Full, Queue


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Doubler:
    def work(self, x):
        return x * 2


def test_actor_pool_map_ordered():
    pool = ActorPool([Doubler.remote() for _ in range(3)])
    out = list(pool.map(lambda a, v: a.work.remote(v), range(10)))
    assert out == [x * 2 for x in range(10)]
    assert pool.num_idle == 3  # all actors returned to the pool


def test_actor_pool_map_unordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = sorted(pool.map_unordered(lambda a, v: a.work.remote(v), range(8)))
    assert out == [x * 2 for x in range(8)]


def test_actor_pool_submit_get_next():
    pool = ActorPool([Doubler.remote()])
    pool.submit(lambda a, v: a.work.remote(v), 10)
    pool.submit(lambda a, v: a.work.remote(v), 20)  # blocks until actor frees
    assert pool.has_next()
    assert pool.get_next(timeout=30) == 20
    assert pool.get_next(timeout=30) == 40
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_queue_roundtrip_and_sharing():
    q = Queue()
    try:
        q.put("a")
        q.put("b")
        assert q.qsize() == 2
        assert q.get() == "a"

        # shared across tasks: a producer task feeds a consumer here
        @ray_tpu.remote
        def producer(queue, n):
            for i in range(n):
                queue.put(i)
            return "done"

        ref = producer.remote(q, 5)
        got = [q.get(timeout=30) for _ in range(6)]  # "b" + 5 produced
        assert got == ["b", 0, 1, 2, 3, 4]
        assert ray_tpu.get(ref) == "done"
    finally:
        q.shutdown()


def test_queue_bounds_and_timeouts():
    q = Queue(maxsize=2)
    try:
        q.put(1)
        q.put(2)
        with pytest.raises(Full):
            q.put_nowait(3)
        with pytest.raises(Full):
            q.put(3, timeout=0.1)
        assert q.full()
        assert q.get_nowait() == 1
        q.put(3)  # space again
        assert q.get() == 2 and q.get() == 3
        with pytest.raises(Empty):
            q.get_nowait()
        with pytest.raises(Empty):
            q.get(timeout=0.1)
    finally:
        q.shutdown()


def test_queue_blocking_get_wakes_on_put():
    q = Queue()
    try:
        result = []

        def consumer():
            result.append(q.get(timeout=30))

        t = threading.Thread(target=consumer)
        t.start()
        q.put("wake")
        t.join(timeout=30)
        assert result == ["wake"]
    finally:
        q.shutdown()


def _square(x):
    return x * x


def _addmul(a, b):
    return a + b, a * b


def test_multiprocessing_pool_map():
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=3) as pool:
        assert pool.map(_square, range(8)) == [x * x for x in range(8)]
        assert pool.starmap(_addmul, [(1, 2), (3, 4)]) == [(3, 2), (7, 12)]
        assert pool.apply(_square, (9,)) == 81
        async_res = pool.map_async(_square, [2, 3])
        assert async_res.get(timeout=60) == [4, 9]
        # process executor = real OS processes, not the driver
        import os

        pids = pool.map(lambda _: os.getpid(), range(3))
        assert all(p != os.getpid() for p in pids)
    with pytest.raises(ValueError, match="closed"):
        pool.map(_square, [1])


def test_dataset_iter_torch_batches():
    import torch

    from ray_tpu import data

    ds = data.range(16, num_blocks=2).map_batches(
        lambda b: {"x": b["item"], "y": b["item"] * 2.0}
    )
    batches = list(ds.iter_torch_batches(4, dtypes={"y": torch.float32}))
    assert len(batches) == 4
    assert isinstance(batches[0]["x"], torch.Tensor)
    assert batches[0]["y"].dtype == torch.float32
    assert batches[1]["x"].tolist() == [4, 5, 6, 7]


def test_empty_waits_do_not_hang():
    from ray_tpu.util.multiprocessing import Pool

    with Pool(2) as pool:
        res = pool.map_async(_square, [])
        res.wait(timeout=5)  # must return immediately, not deadlock
        assert res.get(timeout=5) == []
        assert res.ready()
    # the underlying primitive: wait over zero refs returns at once
    ready, rest = ray_tpu.wait([], num_returns=0, timeout=5)
    assert ready == [] and rest == []
