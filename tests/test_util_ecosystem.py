"""ActorPool + distributed Queue (reference ray.util.actor_pool/queue)."""

import threading

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Empty, Full, Queue


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Doubler:
    def work(self, x):
        return x * 2


def test_actor_pool_map_ordered():
    pool = ActorPool([Doubler.remote() for _ in range(3)])
    out = list(pool.map(lambda a, v: a.work.remote(v), range(10)))
    assert out == [x * 2 for x in range(10)]
    assert pool.num_idle == 3  # all actors returned to the pool


def test_actor_pool_map_unordered():
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = sorted(pool.map_unordered(lambda a, v: a.work.remote(v), range(8)))
    assert out == [x * 2 for x in range(8)]


def test_actor_pool_submit_get_next():
    pool = ActorPool([Doubler.remote()])
    pool.submit(lambda a, v: a.work.remote(v), 10)
    pool.submit(lambda a, v: a.work.remote(v), 20)  # blocks until actor frees
    assert pool.has_next()
    assert pool.get_next(timeout=30) == 20
    assert pool.get_next(timeout=30) == 40
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_queue_roundtrip_and_sharing():
    q = Queue()
    try:
        q.put("a")
        q.put("b")
        assert q.qsize() == 2
        assert q.get() == "a"

        # shared across tasks: a producer task feeds a consumer here
        @ray_tpu.remote
        def producer(queue, n):
            for i in range(n):
                queue.put(i)
            return "done"

        ref = producer.remote(q, 5)
        got = [q.get(timeout=30) for _ in range(6)]  # "b" + 5 produced
        assert got == ["b", 0, 1, 2, 3, 4]
        assert ray_tpu.get(ref) == "done"
    finally:
        q.shutdown()


def test_queue_bounds_and_timeouts():
    q = Queue(maxsize=2)
    try:
        q.put(1)
        q.put(2)
        with pytest.raises(Full):
            q.put_nowait(3)
        with pytest.raises(Full):
            q.put(3, timeout=0.1)
        assert q.full()
        assert q.get_nowait() == 1
        q.put(3)  # space again
        assert q.get() == 2 and q.get() == 3
        with pytest.raises(Empty):
            q.get_nowait()
        with pytest.raises(Empty):
            q.get(timeout=0.1)
    finally:
        q.shutdown()


def test_queue_blocking_get_wakes_on_put():
    q = Queue()
    try:
        result = []

        def consumer():
            result.append(q.get(timeout=30))

        t = threading.Thread(target=consumer)
        t.start()
        q.put("wake")
        t.join(timeout=30)
        assert result == ["wake"]
    finally:
        q.shutdown()
