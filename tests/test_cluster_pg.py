"""Cluster-wide placement groups (2PC across node agents) and Train
gangs hosted BY the cluster — the round-4 verdict's #1 item: "the
cluster and the training stack must become one system".

Reference models: gcs_placement_group_scheduler.h:288 (prepare/commit
across raylets via LeaseStatusTracker) and
train/_internal/backend_executor.py:230 (gang actors inside the PG).
"""

import os
import time

import jax
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.scheduler import PlacementGroupSchedulingStrategy


@pytest.fixture
def gang_cluster():
    """Head (1 CPU, no 'gang' resource) + 2 agents with gang:1 each:
    a 2-bundle gang PG MUST span both agents."""
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, resources={"gang": 1},
               system_config={"node_heartbeat_s": 0.2})
    c.add_node(num_cpus=2, resources={"gang": 1},
               system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def _agent_available(resource):
    """Each agent's view of its OWN available resource (probe task)."""

    @ray_tpu.remote(num_cpus=1)
    def probe():
        from ray_tpu.core.runtime import get_runtime

        node = get_runtime().scheduler.head_node()
        return node.resources.available()

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    out = {}
    rt = ray_tpu.core.runtime.get_runtime()
    for n in rt.scheduler.nodes():
        if n.is_remote and n.resources.total.get(resource, 0.0) > 0:
            avail = ray_tpu.get(
                probe.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(n.node_id)
                ).remote(),
                timeout=60,
            )
            out[n.node_id.hex()] = avail.get(resource, 0.0)
    return out


def test_pg_reserves_across_agents_and_releases(gang_cluster):
    """A 2-bundle gang PG spans both agents: each agent's OWN ledger
    shows the bundle held (2PC prepare landed), and removal returns it."""
    pg = ray_tpu.placement_group(
        [{"gang": 1}, {"gang": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.ready(timeout=10)
    nodes = {b.node.node_id.hex() for b in pg.bundles}
    assert len(nodes) == 2 and all(b.node.is_remote for b in pg.bundles)

    held = _agent_available("gang")
    assert list(held.values()) == [0.0, 0.0], f"agent ledgers: {held}"

    ray_tpu.remove_placement_group(pg)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        restored = _agent_available("gang")
        if list(restored.values()) == [1.0, 1.0]:
            break
        time.sleep(0.1)
    assert list(restored.values()) == [1.0, 1.0], f"not released: {restored}"


def test_pg_atomic_rollback_on_agent_refusal(gang_cluster):
    """A second driver's PG holds one agent's gang slot invisibly to
    this driver; our 2-bundle STRICT_SPREAD PG must fail atomically —
    the OTHER agent's prepared bundle rolls back."""
    import subprocess
    import sys
    import tempfile
    import textwrap

    script = textwrap.dedent(
        """
        import sys, time
        import ray_tpu

        address, flag = sys.argv[1], sys.argv[2]
        ray_tpu.init(address=address, num_cpus=0, detect_accelerators=False)
        deadline = time.monotonic() + 60
        while ray_tpu.cluster_resources().get("gang", 0) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.1)
        pg = ray_tpu.placement_group([{"gang": 1}])
        assert pg.ready(timeout=10)
        open(flag, "w").write("held")
        time.sleep(15)  # hold the slot while the main driver tries
        ray_tpu.shutdown()
        """
    )
    fd, flag = tempfile.mkstemp(prefix="ray_tpu_pgflag_")
    os.close(fd)
    os.unlink(flag)
    second = subprocess.Popen(
        [sys.executable, "-c", script, gang_cluster.address, flag],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.monotonic() + 90
        while not os.path.exists(flag):
            assert second.poll() is None, second.communicate()[0]
            assert time.monotonic() < deadline
            time.sleep(0.1)

        from ray_tpu.core.exceptions import PlacementGroupUnschedulableError

        # Our view still believes both agents have gang:1 free — phase 2
        # at the occupied agent must refuse, and the whole PG must fail.
        with pytest.raises(PlacementGroupUnschedulableError):
            ray_tpu.placement_group(
                [{"gang": 1}, {"gang": 1}], strategy="STRICT_SPREAD"
            )
        # atomicity: the agent that DID grant its bundle rolled back
        held = _agent_available("gang")
        assert sorted(held.values()) == [0.0, 1.0], (
            f"rollback failed, agent ledgers: {held}"
        )
    finally:
        second.kill()
        second.communicate()


def test_task_and_actor_run_inside_remote_bundle(gang_cluster):
    """Work scheduled into a remote bundle executes ON that bundle's
    node, leasing from the reserved pool."""
    pg = ray_tpu.placement_group(
        [{"gang": 1, "CPU": 1}, {"gang": 1, "CPU": 1}],
        strategy="STRICT_SPREAD",
    )
    assert pg.ready(timeout=10)
    agent_pids = {
        rec["node_id"]: rec["pid"]
        for rec in gang_cluster.runtime.cluster.nodes()
        if not rec["is_head"]
    }

    @ray_tpu.remote(num_cpus=1, resources={"gang": 1})
    def whoami():
        return os.getpid()

    for idx, bundle in enumerate(pg.bundles):
        pid = ray_tpu.get(
            whoami.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    pg, placement_group_bundle_index=idx
                )
            ).remote(),
            timeout=60,
        )
        assert pid == agent_pids[bundle.node.node_id.hex()]

    @ray_tpu.remote(num_cpus=1, resources={"gang": 1})
    class Member:
        def where(self):
            return os.getpid()

    member = Member.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=1
        )
    ).remote()
    pid = ray_tpu.get(member.where.remote(), timeout=60)
    assert pid == agent_pids[pg.bundles[1].node.node_id.hex()]
    ray_tpu.kill(member)
    ray_tpu.remove_placement_group(pg)


# Each gang member comes up on its own 1-device CPU backend, immune to
# the parent's XLA_FLAGS and the environment's TPU plugin.
_HOST_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def _make_tiny_train_fn():
    """Builds the train fn INSIDE a function scope so cloudpickle ships
    it by value to agent-hosted actors (a module-level test function
    would pickle by reference to a module agents cannot import)."""

    def _tiny_train_fn(config):
        """Same SPMD program as tests/test_multihost.py, over whatever
        global mesh jax.distributed assembled."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import get_config
        from ray_tpu.parallel import MeshSpec, build_mesh, default_rules
        from ray_tpu.train import (
            create_train_state,
            default_optimizer,
            make_train_step,
            report,
        )

        n_dev = config["n_devices"]
        devices = jax.devices()[:n_dev]
        mesh = build_mesh(MeshSpec(dp=n_dev), devices=devices)
        model_cfg = get_config("llama-tiny").replace(dtype=jnp.float32)
        opt = default_optimizer(1e-3, total_steps=10)
        state, shardings = create_train_state(
            model_cfg, opt, jax.random.PRNGKey(0), mesh, default_rules()
        )
        step = make_train_step(model_cfg, opt, mesh, state_shardings=shardings)

        global_tokens = (
            np.arange(8 * 33, dtype=np.int32).reshape(8, 33) % model_cfg.vocab_size
        )
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec("dp", None))
        if jax.process_count() > 1:
            per = 8 // jax.process_count()
            local = global_tokens[jax.process_index() * per:(jax.process_index() + 1) * per]
            tokens = jax.make_array_from_process_local_data(sharding, local)
        else:
            tokens = jax.device_put(jnp.asarray(global_tokens), sharding)

        losses = []
        for _ in range(3):
            state, metrics = step(state, {"tokens": tokens})
            loss = float(metrics["loss"])
            losses.append(loss)
            try:
                report({"loss": loss})
            except RuntimeError:
                pass
        return losses

    return _tiny_train_fn


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="XLA rejects the 2-process gang on CPU: 'Multiprocess computations "
    "aren't implemented on the CPU backend' (pre-existing since seed)",
)
def test_cluster_hosted_train_gang_matches_single_process(gang_cluster):
    """THE round-5 capstone: a 2-member jax.distributed SPMD gang whose
    member processes are actors hosted by two different cluster agents
    (inside a STRICT_SPREAD PG pinning one bundle per agent), producing
    the same losses as the single-process 2-device run."""
    from ray_tpu.train import ClusterWorkerGroup

    tiny_train_fn = _make_tiny_train_fn()

    # baseline in a throwaway worker process (this process may hold TPU)
    from ray_tpu.train.multihost import MultihostWorkerGroup

    base_group = MultihostWorkerGroup(
        num_workers=1, run_name="gang-base",
        env_per_worker=[{**_HOST_ENV,
                         "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}],
    )
    try:
        base_group.start()
        futs = base_group.run_async(tiny_train_fn, {"n_devices": 2})
        baseline = base_group.finish(futs, timeout=600)[0]
    finally:
        base_group.shutdown()

    group = ClusterWorkerGroup(
        num_workers=2,
        resources_per_worker={"CPU": 1, "gang": 1},
        run_name="cluster-gang",
        env_per_worker=[dict(_HOST_ENV) for _ in range(2)],
    )
    try:
        group.start()
        # one bundle per agent, and the member actors live in processes
        # on those agents (grandchildren of the agent processes)
        bundle_nodes = {b.node.node_id.hex() for b in group.pg.bundles}
        assert len(bundle_nodes) == 2
        assert all(b.node.is_remote for b in group.pg.bundles)

        refs = group.run_async(tiny_train_fn, {"n_devices": 2})
        deadline = time.monotonic() + 600
        cursors = [0, 0]
        reports = []
        while time.monotonic() < deadline:
            polls = group.poll(cursors)
            for i, p in enumerate(polls):
                reports.extend(p["reports"])
                cursors[i] += len(p["reports"])
                assert not p["error"], p["error"]
            if all(p["done"] for p in polls):
                break
            time.sleep(0.2)
        results = group.finish(refs, timeout=60)
    finally:
        group.shutdown()

    # every member computed the same global losses, equal to baseline
    for member_losses in results:
        assert member_losses == pytest.approx(baseline, rel=1e-5)
    # reports streamed back over the actor plane from both ranks
    assert {r[2] for r in reports} == {0, 1}


# ----------------------------------------------------------------- failover
# Node-death recovery: a bundle host dying moves its PG through
# RESERVED -> RESCHEDULING -> RESERVED (re-reserved on a surviving
# node), budgeted bundle actors restart into the re-reserved bundle,
# and a cluster-hosted train gang re-meshes and resumes from its latest
# checkpoint. Node kills go through the chaos harness (kill_node mode),
# so the same injection machinery covers task faults AND host loss.

_CHAOS_KILL_ENV = {
    "RAY_TPU_CHAOS": "kill_node=1,name_filter=chaos-kill,max_injections=1"
}


@pytest.fixture
def failover_cluster():
    """Head (1 CPU) + 3 agents with gang:1 each, armed with a chaos
    kill_node trigger: any task named 'chaos-kill' executed on an agent
    hard-kills that agent (os._exit), simulating host loss. A 2-bundle
    STRICT_SPREAD PG leaves exactly one spare gang-capable agent."""
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {
                "node_stale_s": 2.0,
                "node_heartbeat_s": 0.2,
                "pg_reschedule_backoff_s": 0.2,
            },
        }
    )
    for _ in range(3):
        c.add_node(
            num_cpus=3, resources={"gang": 1},
            system_config={"node_heartbeat_s": 0.2, "node_stale_s": 2.0},
            env=dict(_CHAOS_KILL_ENV),
        )
    c.wait_for_nodes(4)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def _chaos_kill_node(node_id):
    """Kill a node through the chaos harness: dispatch a task named to
    match the agents' kill_node filter, pinned to the victim."""
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    @ray_tpu.remote(num_cpus=0, name="chaos-kill")
    def boom():  # pragma: no cover - the agent dies before returning
        return "unreachable"

    boom.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id)
    ).remote()  # fire and forget: the result never arrives


def _agent_pids(cluster):
    return {
        rec["node_id"]: rec["pid"]
        for rec in cluster.runtime.cluster.nodes()
        if not rec["is_head"]
    }


def _pg_event_states(pg):
    from ray_tpu.util.events import events

    return [
        e["extra"]["state"]
        for e in events().list(source="placement_groups")
        if e.get("extra", {}).get("pg") == pg.id.hex()
        and e["extra"].get("state")
    ]


def test_pg_reschedules_bundle_after_node_death(failover_cluster):
    """Kill bundle 1's host: the PG transitions RESERVED ->
    RESCHEDULING -> RESERVED with the bundle re-reserved (2PC) on the
    spare agent; tasks dispatched into the bundle land there."""
    pg = ray_tpu.placement_group(
        [{"gang": 1}, {"gang": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.ready(timeout=10)
    assert pg.state == "RESERVED"
    agent_pids = _agent_pids(failover_cluster)
    victim_hex = pg.bundles[1].node.node_id.hex()
    spare_hexes = set(agent_pids) - {
        b.node.node_id.hex() for b in pg.bundles
    }
    assert len(spare_hexes) == 1

    _chaos_kill_node(pg.bundles[1].node.node_id)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        node = pg.bundles[1].node
        if (
            pg.state == "RESERVED"
            and node is not None
            and node.node_id.hex() != victim_hex
        ):
            break
        time.sleep(0.1)
    assert pg.state == "RESERVED", (pg.state, pg.failure_reason)
    assert pg.bundles[1].node.node_id.hex() in spare_hexes
    assert pg.reschedules_used >= 1
    assert pg.death_history
    assert victim_hex[:12] in pg.death_history[0]["reason"]

    # the spare agent's own ledger holds the re-reserved bundle (2PC
    # phase 2 landed there), so both surviving gang agents show 0 free
    held = _agent_available("gang")
    assert list(held.values()) == [0.0, 0.0], f"agent ledgers: {held}"

    # work scheduled into the re-reserved bundle executes on the spare
    @ray_tpu.remote(num_cpus=0, resources={"gang": 1})
    def whoami():
        return os.getpid()

    pid = ray_tpu.get(
        whoami.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                pg, placement_group_bundle_index=1
            )
        ).remote(),
        timeout=60,
    )
    assert pid == agent_pids[pg.bundles[1].node.node_id.hex()]

    # the event stream recorded the full transition sequence...
    states = _pg_event_states(pg)
    assert states[0] == "RESERVED"
    assert "RESCHEDULING" in states
    assert states[-1] == "RESERVED"
    # ...and the GCS PG table mirrors the final state cluster-wide
    rec = failover_cluster.runtime.cluster.gcs.pg_state(pg.id.hex())
    assert rec["state"] == "RESERVED"
    assert rec["reschedules_used"] >= 1
    assert rec["death_history"]
    ray_tpu.remove_placement_group(pg)


def test_bundle_actor_restarts_into_rescheduled_bundle(failover_cluster):
    """A max_restarts-budgeted actor living in a bundle follows its
    bundle: node death -> PG re-reserves on the spare -> the actor FSM
    (ALIVE -> RESTARTING -> ALIVE) lands it on the bundle's new host."""
    pg = ray_tpu.placement_group(
        [{"gang": 1, "CPU": 1}, {"gang": 1, "CPU": 1}],
        strategy="STRICT_SPREAD",
    )
    assert pg.ready(timeout=10)
    agent_pids = _agent_pids(failover_cluster)

    @ray_tpu.remote(num_cpus=1, resources={"gang": 1}, max_restarts=1)
    class Member:
        def where(self):
            return os.getpid()

    member = Member.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=1
        )
    ).remote()
    old_pid = ray_tpu.get(member.where.remote(), timeout=60)
    victim_hex = pg.bundles[1].node.node_id.hex()
    assert old_pid == agent_pids[victim_hex]

    _chaos_kill_node(pg.bundles[1].node.node_id)

    new_pid = None
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        try:
            new_pid = ray_tpu.get(member.where.remote(), timeout=30)
            if new_pid != old_pid:
                break
        except Exception:
            time.sleep(0.3)  # death window: calls fail until RESTARTING
    assert new_pid is not None and new_pid != old_pid
    new_hex = pg.bundles[1].node.node_id.hex()
    assert new_hex != victim_hex
    assert new_pid == agent_pids[new_hex]
    ray_tpu.kill(member)
    ray_tpu.remove_placement_group(pg)


@pytest.fixture
def single_agent_cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {
                "node_stale_s": 2.0,
                "node_heartbeat_s": 0.2,
            },
        }
    )
    c.add_node(num_cpus=2, resources={"gang": 1},
               system_config={"node_heartbeat_s": 0.2},
               env=dict(_CHAOS_KILL_ENV))
    c.wait_for_nodes(2)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_pg_budget_exhausted_fails_with_death_history(single_agent_cluster):
    """max_reschedules=0: the first bundle-host death exhausts the
    budget; the PG lands in FAILED and tasks targeting it fail with a
    clear error carrying the death history."""
    from ray_tpu.core.exceptions import OutOfResourcesError

    pg = ray_tpu.placement_group([{"gang": 1}], max_reschedules=0)
    assert pg.ready(timeout=10)
    victim_hex = pg.bundles[0].node.node_id.hex()

    _chaos_kill_node(pg.bundles[0].node.node_id)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and pg.state != "FAILED":
        time.sleep(0.1)
    assert pg.state == "FAILED"
    assert "death history" in pg.failure_reason
    assert victim_hex[:12] in pg.failure_reason
    assert not pg.wait_reserved(timeout=1)

    @ray_tpu.remote(num_cpus=0, resources={"gang": 1})
    def doomed():
        return 1

    with pytest.raises(OutOfResourcesError, match="death history"):
        ray_tpu.get(
            doomed.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(pg)
            ).remote(),
            timeout=30,
        )
    states = _pg_event_states(pg)
    assert states[-1] == "FAILED"
    ray_tpu.remove_placement_group(pg)


def _make_step_train_fn():
    """Checkpoint-aware toy train loop (built in function scope so
    cloudpickle ships it by value to agent-hosted actors): reports a
    decreasing loss per step and resumes from resume_from_step — the
    controller feeds it the latest checkpoint step across restarts."""

    def fn(config):
        import time as _time

        from ray_tpu.train import report

        total = config["total_steps"]
        resume = config.get("resume_from_step")
        start = (resume + 1) if resume is not None else 0
        for step in range(start, total):
            _time.sleep(config["step_s"])
            try:
                report(
                    {"loss": 1.0 / (step + 1.0), "step": step},
                    checkpoint_step=step,
                )
            except RuntimeError:
                pass
        return start

    return fn


def test_cluster_gang_remesh_on_node_death(failover_cluster):
    """THE failover capstone: kill the agent hosting bundle 1 mid-train.
    The PG re-reserves on the spare node, the controller re-meshes the
    gang there with a freshly elected coordinator, training resumes from
    the latest checkpoint (steps never replay), and the loss curve
    continues to the end."""
    import threading

    from ray_tpu.train import (
        ClusterWorkerGroup,
        FailureConfig,
        RunConfig,
        RunStatus,
        ScalingConfig,
        TrainController,
    )

    pg = ray_tpu.placement_group(
        [{"CPU": 1, "gang": 1}, {"CPU": 1, "gang": 1}],
        strategy="STRICT_SPREAD",
    )
    assert pg.ready(timeout=10)
    victim_hex = pg.bundles[1].node.node_id.hex()

    groups = []

    def factory():
        group = ClusterWorkerGroup(
            num_workers=2,
            resources_per_worker={"CPU": 1, "gang": 1},
            run_name="failover-gang",
            env_per_worker=[dict(_HOST_ENV) for _ in range(2)],
            pg=pg,
            init_distributed=False,  # recovery paths under test, not SPMD
            pg_wait_s=60,
        )
        groups.append(group)
        return group

    total_steps = 40
    controller = TrainController(
        _make_step_train_fn(),
        ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1, "gang": 1}
        ),
        RunConfig(name="failover-gang", failure=FailureConfig(max_failures=10)),
        train_config={"total_steps": total_steps, "step_s": 0.25},
        group_factory=factory,
        restart_backoff_s=0.5,
    )
    box = {}
    runner = threading.Thread(
        target=lambda: box.update(result=controller.run()), daemon=True
    )
    runner.start()

    # let training produce a few checkpointed steps, then kill bundle
    # 1's host mid-train
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and len(controller.metrics_history) < 3:
        time.sleep(0.1)
    assert controller.metrics_history, "gang never reported"
    _chaos_kill_node(pg.bundles[1].node.node_id)

    runner.join(timeout=240)
    assert not runner.is_alive(), "controller never finished after failover"
    result = box["result"]
    assert result.status == RunStatus.FINISHED, result.error
    assert result.error is None
    assert result.num_restarts >= 1

    # resumed from the latest checkpoint: steps strictly increase (no
    # replay, no gap) and reach the end; the loss curve continues
    steps = [m["step"] for m in result.metrics_history]
    assert steps[0] == 0
    assert steps[-1] == total_steps - 1
    assert steps == sorted(set(steps)), "steps replayed or reordered"
    losses = [m["loss"] for m in result.metrics_history]
    assert losses == sorted(losses, reverse=True), "loss curve broke"
    assert result.checkpoint_step == total_steps - 1

    # the PG re-reserved off the dead node...
    assert pg.state == "RESERVED"
    survivors = {b.node.node_id.hex() for b in pg.bundles}
    assert victim_hex not in survivors
    assert pg.reschedules_used >= 1
    # ...the re-meshed gang elected a NEW coordinator...
    assert len(groups) >= 2
    assert groups[-1]._coordinator != groups[0]._coordinator
    # ...and the event stream recorded the full transition sequence
    states = _pg_event_states(pg)
    assert states[0] == "RESERVED"
    assert "RESCHEDULING" in states
    assert states[-1] == "RESERVED"
    ray_tpu.remove_placement_group(pg)
