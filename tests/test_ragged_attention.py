"""Ragged paged-attention parity drill (tier-1, interpret mode).

The contract under test (ray_tpu/ops/ragged_paged_attention.py): the
Pallas kernel run in interpret mode and the XLA schedule-replay
reference are BIT-EXACT at f32 — the reference replays the kernel's
block schedule op for op (same dot shapes, same mask constant, same
online-softmax update order), so TPU-vs-CPU numerics questions reduce
to Mosaic codegen, never to algorithm drift. A dense per-sequence
softmax pins the semantics both agree on.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.ragged_paged_attention import (
    ragged_paged_attention,
    ragged_reference_attention,
)
from ray_tpu.serve.llm.paged import paged_attention

BQ = 8


def _mixed_batch(seed=0, Hq=4, Hkv=2, D=16, ps=8, pool=32, maxP=6):
    """The canonical mixed ragged batch: two prefill chunks (one
    page-misaligned, one chunk-aligned continuation), two decode lanes
    (one mid-sequence, one nearly fresh), one inactive lane."""
    rng = np.random.default_rng(seed)
    q_lens = np.array([13, 16, 1, 1, 0], np.int32)
    kv_lens = np.array([13, 48, 37, 5, 0], np.int32)
    counts = np.array([2, 2, 1, 1, 1], np.int32)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
    t = int(counts.sum()) * BQ
    tables = np.zeros((5, maxP), np.int32)
    nxt = 1
    for s in range(5):
        for j in range((int(kv_lens[s]) + ps - 1) // ps):
            tables[s, j] = nxt
            nxt += 1
    assert nxt <= pool
    q = rng.standard_normal((Hq, t, D)).astype(np.float32)
    kp = rng.standard_normal((Hkv, pool, ps, D)).astype(np.float32)
    vp = rng.standard_normal((Hkv, pool, ps, D)).astype(np.float32)
    return (
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(starts), jnp.asarray(counts),
        jnp.asarray(q_lens), jnp.asarray(kv_lens), jnp.asarray(tables),
    ), (q, kp, vp, starts, counts, q_lens, kv_lens, tables)


def test_interpret_kernel_bitwise_matches_reference():
    """The tier-1 parity drill: mixed prefill+decode ragged batch,
    interpret-mode Pallas kernel vs the XLA reference, f32, bit-exact."""
    args, _ = _mixed_batch()
    out_kernel = np.asarray(
        ragged_paged_attention(*args, block_q=BQ, interpret=True)
    )
    out_ref = np.asarray(
        ragged_paged_attention(*args, block_q=BQ, use_kernel=False)
    )
    assert np.array_equal(out_kernel, out_ref), (
        "interpret kernel and schedule-replay reference diverged "
        f"(max diff {np.abs(out_kernel - out_ref).max()})"
    )


def test_reference_matches_dense_softmax_per_sequence():
    """Semantic ground truth: every active sequence's valid rows equal a
    dense causal softmax over its own pages (GQA repeat, positions
    kv_len - q_len + row)."""
    args, (q, kp, vp, starts, counts, q_lens, kv_lens, tables) = _mixed_batch()
    out = np.asarray(ragged_paged_attention(*args, block_q=BQ, use_kernel=False))
    d = q.shape[-1]
    groups = q.shape[0] // kp.shape[0]
    for s in range(len(q_lens)):
        ql, kl = int(q_lens[s]), int(kv_lens[s])
        if ql == 0:
            continue
        rows = slice(int(starts[s]) * BQ, int(starts[s]) * BQ + ql)
        k_seq = np.repeat(kp[:, tables[s]].reshape(kp.shape[0], -1, d)[:, :kl],
                          groups, 0)
        v_seq = np.repeat(vp[:, tables[s]].reshape(vp.shape[0], -1, d)[:, :kl],
                          groups, 0)
        logits = np.einsum("hqd,hkd->hqk", q[:, rows] / math.sqrt(d), k_seq)
        pos = kl - ql + np.arange(ql)
        logits = np.where(
            (np.arange(kl)[None, :] <= pos[:, None])[None], logits, -1e30
        )
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hqk,hkd->hqd", p, v_seq)
        np.testing.assert_allclose(ref, out[:, rows], atol=2e-5, rtol=2e-5)


def test_pad_rows_finite_and_inactive_lanes_zero():
    """Pad rows beyond each region's q_len are finite and deterministic
    (bitwise-pinned by the parity drill above — never NaN, never read by
    callers); fully inactive lanes (q_len == 0) come back as exact zeros
    (the finalize guard skips them, leaving the zero-initialized output)."""
    args, (_, _, _, starts, counts, q_lens, _, _) = _mixed_batch()
    out = np.asarray(ragged_paged_attention(*args, block_q=BQ, interpret=True))
    assert np.isfinite(out).all()
    for s in range(len(q_lens)):
        if int(q_lens[s]) == 0:
            lo = int(starts[s]) * BQ
            hi = lo + int(counts[s]) * BQ
            assert np.all(out[:, lo:hi] == 0.0), f"inactive seq {s} not zeroed"


def test_tp2_shard_map_bitwise_matches_single_device():
    """Satellite: the shard_map-wrapped TP path over a tp=2 CPU mesh is
    bitwise identical to the single-device kernel — heads split across
    shards, each runs the same schedule on its local group."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 CPU devices (conftest forces 8)")
    from jax.sharding import Mesh

    args, _ = _mixed_batch(seed=3)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    out_tp = ragged_paged_attention(*args, block_q=BQ, interpret=True,
                                    mesh=mesh)
    out_one = ragged_paged_attention(*args, block_q=BQ, interpret=True)
    assert np.array_equal(np.asarray(out_tp), np.asarray(out_one))


def test_decode_paged_attention_kernel_path_with_tp_mesh():
    """Satellite regression for the old `use_kernel = False if tp_active`
    pessimization: paged_attention's kernel path must run (and agree with
    the gather reference) under a tp=2 mesh."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 CPU devices (conftest forces 8)")
    from jax.sharding import Mesh

    rng = np.random.default_rng(7)
    b, hq, hkv, d, ps, pool, maxp = 3, 4, 2, 16, 8, 16, 4
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((hkv, pool, ps, d)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((hkv, pool, ps, d)), jnp.float32)
    tables = np.zeros((b, maxp), np.int32)
    tables[0, :2] = [1, 2]
    tables[1, :3] = [3, 4, 5]
    tables[2, :1] = [6]
    lengths = jnp.asarray([11, 20, 3], jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    out_kernel = paged_attention(
        q, kc, vc, jnp.asarray(tables), lengths, page_size=ps,
        interpret=True, mesh=mesh,
    )
    out_ref = paged_attention(
        q, kc, vc, jnp.asarray(tables), lengths, page_size=ps,
        use_kernel=False,
    )
    np.testing.assert_allclose(
        np.asarray(out_kernel), np.asarray(out_ref), atol=2e-5, rtol=2e-5
    )


def test_gather_reference_helper_matches_dispatcher():
    """ragged_reference_attention (the exported schedule-replay helper)
    and the use_kernel=False dispatcher path agree bitwise — callers may
    use either as the pinned reference."""
    args, _ = _mixed_batch(seed=11)
    q, kp, vp, starts, counts, q_lens, kv_lens, tables = args
    sm = 1.0 / math.sqrt(q.shape[-1])
    direct = ragged_reference_attention(
        (q.astype(jnp.float32) * sm).astype(q.dtype), kp, vp,
        starts, counts, q_lens, kv_lens, tables,
        block_q=BQ, max_q_blocks=int(q.shape[1]) // BQ,
    )
    dispatched = ragged_paged_attention(*args, block_q=BQ, use_kernel=False)
    assert np.array_equal(np.asarray(direct), np.asarray(dispatched))
