"""Actor tests: lifecycle, ordering, named actors, failure, restart.

Coverage modeled on the reference python/ray/tests/test_actor.py and
test_actor_failures.py.
"""

import time

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, by=1):
        self.n += by
        return self.n

    def value(self):
        return self.n

    def explode(self):
        raise RuntimeError("actor method error")


def test_actor_basic(runtime):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6
    assert ray_tpu.get(c.value.remote()) == 6


def test_actor_init_args(runtime):
    c = Counter.remote(100)
    assert ray_tpu.get(c.value.remote()) == 100


def test_actor_method_ordering(runtime):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_method_error_does_not_kill_actor(runtime):
    c = Counter.remote()
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(c.explode.remote())
    assert ray_tpu.get(c.inc.remote()) == 1


def test_actor_objectref_args(runtime):
    c = Counter.remote()
    ref = ray_tpu.put(7)
    assert ray_tpu.get(c.inc.remote(ref)) == 7


def test_named_actor(runtime):
    Counter.options(name="global_counter").remote(5)
    handle = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(handle.value.remote()) == 5
    assert "global_counter" in [a["name"] for a in ray_tpu.list_actors()]


def test_kill_actor(runtime):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    time.sleep(0.05)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=5)


def test_actor_restart(runtime):
    c = Counter.options(max_restarts=1).remote(10)
    assert ray_tpu.get(c.inc.remote()) == 11
    # Simulated process failure -> restart with fresh state.
    ray_tpu.kill(c, no_restart=False)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        try:
            if ray_tpu.get(c.value.remote(), timeout=5) == 10:
                break
        except ray_tpu.RayTpuError:
            time.sleep(0.02)
    assert ray_tpu.get(c.value.remote(), timeout=5) == 10


def test_actor_init_failure(runtime):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("cannot construct")

        def m(self):
            return 1

    b = Bad.remote()
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(b.m.remote(), timeout=5)


def test_max_concurrency(runtime):
    @ray_tpu.remote(max_concurrency=4)
    class Parallel:
        def block(self, t):
            time.sleep(t)
            return True

    p = Parallel.remote()
    start = time.monotonic()
    refs = [p.block.remote(0.2) for _ in range(4)]
    assert all(ray_tpu.get(refs))
    # Sequential would be >= 0.8s; concurrent should be well under.
    assert time.monotonic() - start < 0.6


def test_actor_resources_held(runtime):
    @ray_tpu.remote(num_cpus=8)
    class Hog:
        def ping(self):
            return "pong"

    h = Hog.remote()
    assert ray_tpu.get(h.ping.remote()) == "pong"
    avail = ray_tpu.available_resources()
    assert avail["CPU"] == 0.0
    ray_tpu.kill(h)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if ray_tpu.available_resources()["CPU"] == 8.0:
            break
        time.sleep(0.02)
    assert ray_tpu.available_resources()["CPU"] == 8.0


def test_named_actor_name_released_on_init_failure(runtime):
    """Regression: self-death (init failure) must release the name."""

    @ray_tpu.remote
    class Bad2:
        def __init__(self):
            raise ValueError("nope")

        def m(self):
            return 1

    b = Bad2.options(name="doomed").remote()
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(b.m.remote(), timeout=5)
    # Name must become reusable.
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        try:
            Counter.options(name="doomed").remote(1)
            break
        except ValueError:
            time.sleep(0.02)
    assert ray_tpu.get(ray_tpu.get_actor("doomed").value.remote()) == 1


def test_duplicate_name_raises_without_leak(runtime):
    Counter.options(name="unique").remote()
    before = ray_tpu.available_resources()["CPU"]
    with pytest.raises(ValueError):
        Counter.options(name="unique").remote()
    time.sleep(0.1)
    assert ray_tpu.available_resources()["CPU"] == before


def test_actor_infeasible_placement_dies(runtime):
    @ray_tpu.remote(num_cpus=999)
    class Huge:
        def m(self):
            return 1

    pg = ray_tpu.placement_group([{"CPU": 1}])
    h = Huge.options(
        scheduling_strategy=ray_tpu.PlacementGroupSchedulingStrategy(pg)
    ).remote()
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(h.m.remote(), timeout=5)
    ray_tpu.remove_placement_group(pg)


def test_actor_method_wrong_num_returns_errors(runtime):
    @ray_tpu.remote
    class OneVal:
        def one(self):
            return (1,)

    a = OneVal.remote()
    r1, r2 = a.one.options(num_returns=2).remote()
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(r2, timeout=5)
