"""Streaming generators (num_returns="streaming").

Reference parity: ObjectRefStream / TryReadObjectRefStream
(/root/reference/src/ray/core_worker/core_worker.h:273, task_manager.h:67).
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import GetTimeoutError, ObjectRefGenerator, TaskError


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


def test_generator_task_streams_in_order():
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    stream = gen.remote(5)
    assert isinstance(stream, ObjectRefGenerator)
    values = [ray_tpu.get(ref) for ref in stream]
    assert values == [0, 1, 4, 9, 16]
    assert stream.completed()
    assert stream.total_yielded() == 5


def test_consumer_overlaps_producer():
    release = threading.Event()

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        release.wait(5)
        yield "second"

    stream = slow_gen.remote()
    # first item must arrive while the producer is still blocked
    first = stream.next_ready(timeout=5)
    assert ray_tpu.get(first) == "first"
    assert not stream.completed()
    release.set()
    assert ray_tpu.get(next(stream)) == "second"
    with pytest.raises(StopIteration):
        next(stream)


def test_mid_stream_error_surfaces_after_good_items():
    @ray_tpu.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        yield 2
        raise RuntimeError("boom at item 3")

    stream = bad_gen.remote()
    assert ray_tpu.get(next(stream)) == 1
    assert ray_tpu.get(next(stream)) == 2
    with pytest.raises(TaskError, match="boom"):
        next(stream)


def test_next_ready_timeout():
    @ray_tpu.remote(num_returns="streaming")
    def stuck():
        time.sleep(10)
        yield 1

    stream = stuck.remote()
    with pytest.raises(GetTimeoutError):
        stream.next_ready(timeout=0.1)


def test_streaming_with_retries_resumes_stream():
    attempts = {"n": 0}

    @ray_tpu.remote(num_returns="streaming", max_retries=2, retry_exceptions=True)
    def flaky_gen():
        attempts["n"] += 1
        yield "a"
        yield "b"
        if attempts["n"] == 1:
            raise RuntimeError("transient")
        yield "c"

    stream = flaky_gen.remote()
    values = [ray_tpu.get(r) for r in stream]
    # the retry must not duplicate already-delivered items
    assert values == ["a", "b", "c"]
    assert attempts["n"] == 2


def test_actor_method_streaming():
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.base = 100

        def stream(self, n):
            for i in range(n):
                yield self.base + i

        def bump(self):
            self.base += 1
            return self.base

    c = Counter.remote()
    stream = c.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in stream] == [100, 101, 102]
    # mailbox ordering still holds: bump after the stream completes
    assert ray_tpu.get(c.bump.remote()) == 101


def test_actor_death_fails_stream():
    started = threading.Event()

    @ray_tpu.remote
    class Streamer:
        def stream(self):
            started.set()
            yield 1
            time.sleep(30)
            yield 2

    s = Streamer.remote()
    stream = s.stream.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(stream)) == 1
    started.wait(5)
    ray_tpu.kill(s)
    # queued-but-never-produced items surface the death; the thread-based
    # actor cannot interrupt the running generator, but new consumers of
    # the stream must not hang forever: the item-2 wait must end in error.
    with pytest.raises(Exception):
        stream.next_ready(timeout=60)


def test_streaming_rejects_process_executor():
    @ray_tpu.remote(num_returns="streaming", executor="process")
    def gen():
        yield 1

    with pytest.raises(ValueError, match="thread executor"):
        gen.remote()


def test_streaming_non_iterable_is_error():
    @ray_tpu.remote(num_returns="streaming")
    def not_a_gen():
        return 42

    stream = not_a_gen.remote()
    with pytest.raises(TaskError, match="iterable"):
        next(stream)


def test_many_items_values_remain_gettable():
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(200):
            yield i

    stream = gen.remote()
    refs = list(stream)
    assert len(refs) == 200
    # refs stay valid after the stream is exhausted
    assert ray_tpu.get(refs[7]) == 7
    assert ray_tpu.get(refs[-1]) == 199
