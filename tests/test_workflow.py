"""Workflow: DAG execution, persistence, crash-resume semantics."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(autouse=True)
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()


def test_linear_dag(tmp_path):
    @workflow.step
    def add(a, b):
        return a + b

    @workflow.step
    def double(x):
        return 2 * x

    out = double.step(add.step(1, 2))
    assert workflow.run(out, storage=str(tmp_path), workflow_id="lin") == 6


def test_diamond_dag_runs_shared_step_once(tmp_path):
    calls = tmp_path / "calls"
    calls.mkdir()

    @workflow.step
    def source():
        (calls / f"src_{len(os.listdir(calls))}").touch()
        return 10

    @workflow.step
    def left(x):
        return x + 1

    @workflow.step
    def right(x):
        return x + 2

    @workflow.step
    def join(a, b):
        return a * b

    s = source.step()
    out = join.step(left.step(s), right.step(s))
    assert workflow.run(out, storage=str(tmp_path), workflow_id="dia") == 11 * 12
    # the shared upstream step executed exactly once
    assert len(os.listdir(calls)) == 1


def test_resume_skips_completed_steps(tmp_path):
    progress = tmp_path / "progress.txt"

    @workflow.step
    def expensive():
        progress.write_text(progress.read_text() + "E" if progress.exists() else "E")
        return 5

    @workflow.step
    def flaky(x):
        if not (tmp_path / "fixed").exists():
            raise RuntimeError("crash on first run")
        return x * 10

    dag = flaky.step(expensive.step())
    with pytest.raises(Exception):
        workflow.run(dag, storage=str(tmp_path), workflow_id="wf")
    # expensive committed before the crash
    assert "E" == progress.read_text()
    assert any(s.startswith("expensive") for s in workflow.list_completed(str(tmp_path), "wf"))

    (tmp_path / "fixed").touch()
    assert workflow.run(dag, storage=str(tmp_path), workflow_id="wf") == 50
    # expensive did NOT re-run on resume
    assert "E" == progress.read_text()


def test_different_args_are_different_steps(tmp_path):
    @workflow.step
    def inc(x):
        return x + 1

    assert workflow.run(inc.step(1), storage=str(tmp_path), workflow_id="a") == 2
    assert workflow.run(inc.step(10), storage=str(tmp_path), workflow_id="a") == 11
    completed = workflow.list_completed(str(tmp_path), "a")
    assert len(completed) == 2


def test_flaky_step_retries_then_succeeds(tmp_path):
    """Per-step max_retries: a step that raises is re-run as a task
    retry until it succeeds; the persisted result is the good one."""
    attempts = tmp_path / "attempts"

    @workflow.step(max_retries=3)
    def flaky():
        n = int(attempts.read_text()) if attempts.exists() else 0
        attempts.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError(f"boom #{n}")
        return "survived"

    out = workflow.run(
        flaky.step(), storage=str(tmp_path), workflow_id="retry"
    )
    assert out == "survived"
    assert int(attempts.read_text()) == 3  # 2 failures + 1 success
    assert any(
        s.startswith("flaky")
        for s in workflow.list_completed(str(tmp_path), "retry")
    )


def test_retry_budget_exhausted_propagates(tmp_path):
    @workflow.step(max_retries=1)
    def always_fails():
        raise RuntimeError("permanently broken")

    with pytest.raises(Exception, match="permanently broken"):
        workflow.run(
            always_fails.step(), storage=str(tmp_path), workflow_id="budget"
        )
    assert workflow.list_completed(str(tmp_path), "budget") == []


def test_hung_step_times_out(tmp_path):
    import time

    @workflow.step(timeout_s=0.5)
    def hung():
        time.sleep(60)
        return 1

    t0 = time.monotonic()
    with pytest.raises(workflow.WorkflowStepTimeout, match="hung"):
        workflow.run(hung.step(), storage=str(tmp_path), workflow_id="hang")
    assert time.monotonic() - t0 < 30  # nowhere near the 60s sleep


def test_step_options_override(tmp_path):
    calls = tmp_path / "calls"

    @workflow.step
    def sometimes():
        n = int(calls.read_text()) if calls.exists() else 0
        calls.write_text(str(n + 1))
        if n < 1:
            raise RuntimeError("first call fails")
        return "ok"

    node = sometimes.options(max_retries=2).step()
    assert node.max_retries == 2
    assert workflow.run(node, storage=str(tmp_path), workflow_id="opt") == "ok"
