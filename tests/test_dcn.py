"""Cross-slice (DCN) transfer service: state replication to a peer node
overlapping with ongoing compute (reference: the slow-network half of
the comm stack — background checkpoint/state movement over TCP while
NCCL/ICI carries the hot path).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.parallel import CrossSliceReplicator, fetch_replica


@pytest.fixture
def cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 2,
            "_system_config": {"node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(2)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_replicates_pytree_to_peer_and_overlaps(cluster):
    peer = next(n for n in cluster.runtime.scheduler.nodes() if n.is_remote)
    state = {
        "params": {"w": np.arange(500_000, dtype=np.float32),
                   "b": np.ones(128, dtype=np.float32)},
        "step": 7,
    }
    rep = CrossSliceReplicator(peer.agent_addr)
    try:
        t0 = time.perf_counter()
        rep.replicate_async("trainstate", state)
        submit_latency = time.perf_counter() - t0
        # the call must NOT block on the 2MB transfer: compute keeps going
        assert submit_latency < 0.05, submit_latency
        assert rep.wait(timeout=60)
        assert rep.stats["replicated"] == 1
        assert rep.stats["bytes"] >= 2_000_000

        # the peer resolves the replica from ITS OWN store (a probe task
        # executes fetch_replica inside the agent process)
        @ray_tpu.remote(num_cpus=1)
        def probe():
            from ray_tpu.parallel import fetch_replica

            replica = fetch_replica("trainstate")
            return (
                float(replica["params"]["w"].sum()),
                int(replica["step"]),
            )

        from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

        total, step = ray_tpu.get(
            probe.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(peer.node_id)
            ).remote(),
            timeout=60,
        )
        assert total == float(np.arange(500_000, dtype=np.float32).sum())
        assert step == 7
    finally:
        rep.close()


def test_latest_snapshot_supersedes_queued(cluster):
    """The mirror wants the LATEST state: snapshots accepted while a
    transfer is in flight replace any queued-but-unstarted one."""
    peer = next(n for n in cluster.runtime.scheduler.nodes() if n.is_remote)
    rep = CrossSliceReplicator(peer.agent_addr)
    try:
        big = np.ones(2_000_000, dtype=np.float64)  # 16 MB: takes a beat
        for version in range(6):
            rep.replicate_async("s", {"v": version, "payload": big})
        assert rep.wait(timeout=120)
        # fewer transfers than submissions, and the LAST version landed
        assert rep.stats["replicated"] < 6
        assert rep.stats["superseded"] >= 1

        @ray_tpu.remote(num_cpus=1)
        def version():
            from ray_tpu.parallel import fetch_replica

            return fetch_replica("s")["v"]

        from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

        v = ray_tpu.get(
            version.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(peer.node_id)
            ).remote(),
            timeout=60,
        )
        assert v == 5
    finally:
        rep.close()


def test_fetch_replica_missing_raises(cluster):
    with pytest.raises(KeyError, match="no replica"):
        fetch_replica("never-sent", runtime=cluster.runtime)


def test_quantized_replication_cuts_wire_bytes(cluster):
    """quantize="int8" block-quantizes float leaves before the DCN push
    (~4x fewer wire bytes) and fetch_replica dequantizes transparently;
    small and non-float leaves pass through exact."""
    peer = next(n for n in cluster.runtime.scheduler.nodes() if n.is_remote)
    rng = np.random.default_rng(0)
    state = {
        "params": {"w": rng.standard_normal(500_000).astype(np.float32)},
        "ids": np.arange(100_000, dtype=np.int32),  # non-float: exact
        "small": rng.standard_normal(16).astype(np.float32),  # tiny: exact
        "step": 11,
    }
    rep = CrossSliceReplicator(peer.agent_addr, quantize="int8")
    try:
        rep.replicate_async("qstate", state)
        assert rep.wait(timeout=60)
        assert rep.stats["replicated"] == 1
        # wire bytes ~= w int8 (500k) + scales + ids (400k) + small/meta,
        # vs 2.4 MB raw: the float payload shrank ~4x
        assert rep.stats["raw_bytes"] >= 2_400_000
        assert rep.stats["bytes"] < rep.stats["raw_bytes"] * 0.45

        @ray_tpu.remote(num_cpus=1)
        def probe():
            from ray_tpu.parallel import fetch_replica

            replica = fetch_replica("qstate")
            return (
                replica["params"]["w"],
                replica["ids"][-1],
                replica["small"],
                replica["step"],
            )

        from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

        w, last_id, small, step = ray_tpu.get(
            probe.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(peer.node_id)
            ).remote(),
            timeout=60,
        )
        assert w.shape == (500_000,) and w.dtype == np.float32
        # blockwise int8: relative error bounded by the quantization step
        denom = max(np.abs(state["params"]["w"]).max(), 1e-9)
        assert np.abs(w - state["params"]["w"]).max() / denom < 0.005
        assert last_id == 99_999
        np.testing.assert_array_equal(small, state["small"])
        assert step == 11
    finally:
        rep.close()
