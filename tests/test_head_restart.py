"""Head fault-tolerance suite (round-4 verdict #10, grown into the head
fault-tolerance plane): WAL durability beats snapshot-only restore, torn
journal tails are quarantined, epoch fencing rejects stale writers,
clients degrade with typed errors through an outage, the serve router
keeps dispatching on cached membership, a restarted head reconciles
restored-but-gone state, and the chaos kill_head capstone drives serve
traffic and KV writes through a head SIGKILL + restore with zero
acknowledged-write loss.

Reference: Redis-backed GCS restart (gcs_table_storage.h:275,
gcs_redis_failure_detector.h:35) where raylets outlive the GCS.
"""

import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

import pytest

from ray_tpu.core.exceptions import HeadUnavailableError, StaleEpochError
from ray_tpu.core.gcs import GcsWal, GlobalControlStore
from ray_tpu.core.gcs_service import GcsClient, serve_gcs


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
        "RAY_TPU_NODE_HEARTBEAT_S": "0.2", "RAY_TPU_NODE_STALE_S": "2.5",
        "RAY_TPU_GCS_SNAPSHOT_INTERVAL_S": "0.5"}
_ENV.pop("RAY_TPU_CHAOS", None)

_OBSERVER = textwrap.dedent(
    """
    import sys, time
    import ray_tpu

    address, resource, want = sys.argv[1], sys.argv[2], float(sys.argv[3])
    ray_tpu.init(address=address, num_cpus=0, detect_accelerators=False)
    deadline = time.monotonic() + 60
    while ray_tpu.cluster_resources().get(resource, 0) < want:
        assert time.monotonic() < deadline, (
            f"never saw {resource}>={want}: {ray_tpu.cluster_resources()}"
        )
        time.sleep(0.2)

    @ray_tpu.remote(num_cpus=0, resources={resource: 1})
    def where():
        import os
        return os.getpid()

    pid = ray_tpu.get(where.remote(), timeout=60)
    ray_tpu.shutdown()
    print(f"OBSERVER-OK {pid}")
    """
)


def _spawn(cmd, log, env=None):
    return subprocess.Popen(
        cmd, env=env or _ENV, stdout=log, stderr=subprocess.STDOUT, text=True
    )


def _wait_line(path, needle, timeout=90, proc=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            break
        with open(path) as f:
            if needle in f.read():
                return
        time.sleep(0.2)
    with open(path) as f:
        raise AssertionError(f"never saw {needle!r} in:\n{f.read()}")


def _terminate(*procs):
    for proc in procs:
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


# --------------------------------------------------------------------------
# durability: WAL + snapshot
# --------------------------------------------------------------------------


def test_wal_replay_beats_snapshot_only(tmp_path):
    """Every acknowledged mutation AFTER the last snapshot comes back from
    the journal; a snapshot-only restore provably loses them."""
    snap = str(tmp_path / "gcs.snap")
    wal = snap + ".wal"

    a = GlobalControlStore()
    a.attach_wal(wal)
    a.kv.put("pre", 1)
    a.snapshot(snap)
    # mutations the snapshot never saw
    a.kv.put("post", {"x": 2})
    a.kv.put("pre", "rewritten")
    a.kv.delete("pre")
    a.register_named_actor("late-actor", object())

    snap_only = GlobalControlStore()
    snap_only.restore(snap, wal_path=None)
    assert snap_only.kv.get("pre") == 1  # stale: the crash would lose data
    assert snap_only.kv.get("post") is None

    b = GlobalControlStore()
    b.restore(snap, wal_path=wal)
    assert b.kv.get("post") == {"x": 2}
    assert b.kv.get("pre") is None  # the delete replayed too
    # named-actor registrations journal as placeholders: the NAME survives
    # (handles are process-local and must be re-created)
    assert "late-actor" in b.list_named_actors()
    assert b.last_restore["wal_records_applied"] >= 3


def test_wal_only_restart_without_snapshot(tmp_path):
    """A head that dies before its first snapshot still recovers every
    acknowledged write from the journal alone."""
    wal = str(tmp_path / "gcs.snap.wal")
    a = GlobalControlStore()
    a.attach_wal(wal)
    for i in range(20):
        a.kv.put(f"k{i}", i, namespace="drill")
    a.kv.delete("k3", namespace="drill")

    b = GlobalControlStore()
    applied = b.replay_wal(wal, -1)
    assert applied == 21
    assert b.kv.get("k7", namespace="drill") == 7
    assert b.kv.get("k3", namespace="drill") is None


def test_torn_wal_tail_is_quarantined(tmp_path):
    """A torn tail (head died mid-append) must not poison replay: the
    valid prefix is applied, the garbage is moved aside for postmortem,
    and the journal keeps accepting appends with continuous seqs."""
    wal = str(tmp_path / "gcs.snap.wal")
    a = GlobalControlStore()
    a.attach_wal(wal)
    a.kv.put("good", 1)
    a.kv.put("also-good", 2)
    a.detach_wal()
    with open(wal, "ab") as f:
        f.write(b"\x00\x00\x00\x09torn-mid-append")

    # replay of the torn file applies the valid prefix and reports the tail
    b = GlobalControlStore()
    assert b.replay_wal(wal, -1) == 2
    assert b.kv.get("good") == 1 and b.kv.get("also-good") == 2
    assert b.last_restore["wal_quarantined_bytes"] > 0

    # REOPENING the journal (the restarted head attaching it) moves the
    # garbage aside — never silently discarded — and truncates
    reopened = GcsWal(wal)
    assert reopened.quarantined_bytes > 0
    assert os.path.exists(wal + ".quarantine")
    assert reopened.last_seq == 2  # seq resumes after the valid prefix
    reopened.close()


def test_snapshot_compacts_wal(tmp_path):
    """Snapshots are the WAL's compaction point: records the snapshot
    covers are dropped, and snapshot + compacted journal still restores
    everything."""
    snap = str(tmp_path / "gcs.snap")
    wal = snap + ".wal"
    a = GlobalControlStore()
    a.attach_wal(wal)
    for i in range(50):
        a.kv.put(f"bulk{i}", "x" * 200)
    size_before = os.path.getsize(wal)
    a.snapshot(snap)
    assert os.path.getsize(wal) < size_before
    a.kv.put("after-compact", 1)

    b = GlobalControlStore()
    b.restore(snap, wal_path=wal)
    assert b.kv.get("bulk49") == "x" * 200
    assert b.kv.get("after-compact") == 1
    # only the post-snapshot record should have replayed
    assert b.last_restore["wal_records_applied"] == 1


def test_unpicklable_keys_warn_once(tmp_path, caplog):
    """Process-local values (locks, sockets) are legitimately not durable;
    the snapshot and the journal each say so exactly ONCE per key instead
    of spamming every interval."""
    snap = str(tmp_path / "gcs.snap")
    store = GlobalControlStore()
    store.attach_wal(snap + ".wal")
    with caplog.at_level(logging.WARNING, logger="ray_tpu.core.gcs"):
        store.kv.put("lockref", threading.Lock())
        store.kv.put("lockref", threading.Lock())  # journal warn: once
        store.kv.put("plain", 1)
        store.snapshot(snap)
        store.snapshot(snap)  # snapshot warn: once
    snap_warns = [r for r in caplog.records
                  if "skipping unpicklable" in r.message]
    wal_warns = [r for r in caplog.records
                 if "cannot journal" in r.message]
    assert len(snap_warns) == 1, caplog.text
    assert len(wal_warns) == 1, caplog.text
    # the durable keys still made it
    b = GlobalControlStore()
    b.restore(snap, wal_path=snap + ".wal")
    assert b.kv.get("plain") == 1


# --------------------------------------------------------------------------
# epoch fencing + typed degraded mode (real RPC)
# --------------------------------------------------------------------------


def test_epoch_fence_rejects_stale_writer():
    """A writer carrying a pre-restart epoch is rejected with the typed,
    NON-retryable StaleEpochError; a live client re-adopts and proceeds."""
    store = GlobalControlStore()
    server = serve_gcs(store, port=0)
    try:
        zombie = GcsClient(server.url, retry_window_s=1.0)
        zombie.adopt_epoch()
        zombie.pin_epoch(zombie.epoch)  # simulate a pre-restart process

        store.bump_epoch()  # the head restarted underneath it

        with pytest.raises(StaleEpochError) as exc_info:
            zombie.kv_put("fenced", 1)
        # fencing must NOT look like a transient outage, or retry loops
        # would hammer the head with doomed writes
        assert not isinstance(exc_info.value, OSError)
        assert store.kv.get("fenced") is None

        fresh = GcsClient(server.url, retry_window_s=1.0)
        fresh.adopt_epoch()
        assert fresh.epoch == store.current_epoch()
        assert fresh.kv_put("fenced", 2)
        assert store.kv.get("fenced") == 2
    finally:
        server.stop()


def test_head_outage_is_typed_and_transitions_fire():
    """While the head is down every client call fails with the typed
    HeadUnavailableError (an OSError, so legacy handlers still catch it),
    and the client fires exactly one unreachable + one reconnected
    transition across the outage."""
    port = _free_port()
    store = GlobalControlStore()
    server = serve_gcs(store, port=port)
    states = []
    client = GcsClient(f"127.0.0.1:{port}", retry_window_s=0.5)
    client.on_head_state(lambda state, outage_s: states.append(state))
    try:
        assert client.kv_put("before", 1)
        server.stop()
        for _ in range(2):  # repeated failures: still ONE transition
            with pytest.raises(HeadUnavailableError) as exc_info:
                client.kv_get("before")
            assert isinstance(exc_info.value, ConnectionError)
        assert client.outage_s() > 0.0

        server = _rebind(store, port)
        deadline = time.monotonic() + 10
        while True:
            try:
                assert client.kv_get("before") == 1
                break
            except HeadUnavailableError:
                assert time.monotonic() < deadline
        assert client.outage_s() == 0.0
        assert states == ["unreachable", "reconnected"]
    finally:
        server.stop()


def _rebind(store, port, attempts=50):
    """Restart a GCS server on the SAME port (the restore contract: agents
    reconnect to the address they already hold)."""
    for i in range(attempts):
        try:
            return serve_gcs(store, port=port)
        except OSError:
            time.sleep(0.1)
    raise AssertionError(f"could not rebind port {port}")


def test_subscribe_poll_loop_survives_head_outage():
    """The long-poll subscription loop must ride through a head restart:
    keep the thread alive on transient RPC errors, back off, and resume
    from the SAME cursor so no message is dropped (regression: the loop
    previously died on the first transient error)."""
    port = _free_port()
    store = GlobalControlStore()
    server = serve_gcs(store, port=port)
    got = []
    stop = threading.Event()
    sub = GcsClient(f"127.0.0.1:{port}", retry_window_s=0.3)
    thread = threading.Thread(
        target=sub.subscribe_poll_loop,
        args=("drill", got.append),
        kwargs={"period_s": 0.05, "stop_event": stop},
        daemon=True,
    )
    thread.start()
    try:
        store.pubsub.publish("drill", "m1")
        _wait_until(lambda: "m1" in got)

        server.stop()
        time.sleep(1.0)  # several failed polls worth of outage
        assert thread.is_alive(), "poll loop died during the outage"
        store.pubsub.publish("drill", "m2")  # published while subscriber was cut off
        server = _rebind(store, port)
        store.pubsub.publish("drill", "m3")

        _wait_until(lambda: "m3" in got)
        assert got == ["m1", "m2", "m3"]  # cursor resumed: nothing dropped
        assert thread.is_alive()
    finally:
        stop.set()
        thread.join(timeout=5)
        server.stop()


def _wait_until(predicate, timeout=15.0):
    deadline = time.monotonic() + timeout
    while not predicate():
        assert time.monotonic() < deadline, "condition never became true"
        time.sleep(0.05)


def test_router_grace_window_keeps_cached_replicas(monkeypatch):
    """During a head outage the controller computes EMPTY membership
    (control-plane blindness, not replica death); inside the grace window
    the router must keep serving on cached handles, and past it the empty
    set is believed."""
    from ray_tpu.serve import router as router_mod
    from ray_tpu.core.config import cfg

    class _FakeActorId:
        def __init__(self, h):
            self._h = h

        def hex(self):
            return self._h

    class _FakeReplica:
        def __init__(self, h):
            self._actor_id = _FakeActorId(h)

    rset = router_mod.ReplicaSet("drill-deploy")
    r1 = _FakeReplica("aa" * 16)
    rset.set_replicas([r1])

    # head down 5s: inside the grace window -> cached membership survives
    monkeypatch.setattr(router_mod, "_head_outage_s", lambda: 5.0)
    rset.set_replicas([])
    assert rset.pick() is r1

    # outage exceeded the grace window -> the empty set is believed
    monkeypatch.setattr(
        router_mod, "_head_outage_s",
        lambda: float(cfg.head_outage_grace_s) + 1.0)
    rset.set_replicas([])
    with rset._lock:
        assert rset._replicas == []


# --------------------------------------------------------------------------
# multi-process drills
# --------------------------------------------------------------------------


def test_head_restart_restores_surviving_agent():
    tmp = tempfile.mkdtemp(prefix="ray_tpu_headrestart_")
    snap = os.path.join(tmp, "gcs.snap")
    port = _free_port()
    address = f"127.0.0.1:{port}"
    head_log = os.path.join(tmp, "head.log")
    agent_log = os.path.join(tmp, "agent.log")

    head_cmd = [
        sys.executable, "-m", "ray_tpu", "--no-tpu", "start", "--head",
        "--port", str(port), "--num-cpus", "1", "--snapshot-path", snap,
    ]
    head = _spawn(head_cmd, open(head_log, "w"))
    agent = None
    try:
        _wait_line(head_log, "head up", proc=head)
        agent = _spawn(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
             "--address", address, "--num-cpus", "2",
             "--resources", '{"pet": 3}'],
            open(agent_log, "w"),
        )
        _wait_line(agent_log, "joined", proc=agent)

        # observer 1: the agent's resources are visible pre-kill
        out = subprocess.run(
            [sys.executable, "-c", _OBSERVER, address, "pet", "3"],
            env=_ENV, capture_output=True, text=True, timeout=120,
        )
        assert "OBSERVER-OK" in out.stdout, out.stdout + out.stderr
        agent_pid_1 = int(out.stdout.split("OBSERVER-OK")[1].strip())
        assert agent_pid_1 == agent.pid

        # give the snapshot loop a beat to persist the node table
        time.sleep(2.0)

        # kill the head hard; the agent keeps running (heartbeats warn)
        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)
        time.sleep(1.0)
        assert agent.poll() is None, "agent must survive head death"

        # restart the head from the snapshot, same port
        head = _spawn(head_cmd + ["--restore"], open(head_log, "a"))
        _wait_line(head_log, "head up", proc=head)

        # observer 2: the surviving agent (same pid!) re-registered and
        # still executes work — no agent restart happened
        out = subprocess.run(
            [sys.executable, "-c", _OBSERVER, address, "pet", "3"],
            env=_ENV, capture_output=True, text=True, timeout=120,
        )
        assert "OBSERVER-OK" in out.stdout, out.stdout + out.stderr
        agent_pid_2 = int(out.stdout.split("OBSERVER-OK")[1].strip())
        assert agent_pid_2 == agent.pid == agent_pid_1
    finally:
        _terminate(head, agent)


@pytest.mark.slow
def test_head_restart_reconciles_lost_state():
    """Restore brings back a node that died DURING the outage plus actor
    and placement-group records it owned. After the reconcile grace the
    head must purge the dead node, release its actor records, and fail
    its placement groups — WITHOUT touching the survivor, whose process
    never restarts."""
    tmp = tempfile.mkdtemp(prefix="ray_tpu_reconcile_")
    snap = os.path.join(tmp, "gcs.snap")
    port = _free_port()
    address = f"127.0.0.1:{port}"
    env = {**_ENV, "RAY_TPU_HEAD_RECONCILE_GRACE_S": "3"}
    head_log = os.path.join(tmp, "head.log")

    head_cmd = [
        sys.executable, "-m", "ray_tpu", "--no-tpu", "start", "--head",
        "--port", str(port), "--num-cpus", "1", "--snapshot-path", snap,
    ]
    head = _spawn(head_cmd, open(head_log, "w"), env=env)
    survivor = doomed = None
    try:
        _wait_line(head_log, "head up", proc=head)
        survivor_log = os.path.join(tmp, "survivor.log")
        doomed_log = os.path.join(tmp, "doomed.log")
        survivor = _spawn(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
             "--address", address, "--num-cpus", "1",
             "--resources", '{"pet": 1}'],
            open(survivor_log, "w"), env=env)
        doomed = _spawn(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
             "--address", address, "--num-cpus", "1",
             "--resources", '{"gone": 1}'],
            open(doomed_log, "w"), env=env)
        _wait_line(survivor_log, "joined", proc=survivor)
        _wait_line(doomed_log, "joined", proc=doomed)

        client = GcsClient(address, retry_window_s=5.0)
        nodes = {
            h: client.kv_get(h, namespace="_nodes")
            for h in client.kv_keys("*", namespace="_nodes")
        }
        doomed_hex = next(
            h for h, info in nodes.items()
            if info and info.get("resources", {}).get("gone"))
        survivor_hex = next(
            h for h, info in nodes.items()
            if info and info.get("resources", {}).get("pet"))

        # records the doomed node owns: an actor registration and a
        # placement group — reconciliation must release both
        client.kv_put("drill/ghost",
                      {"node_hex": doomed_hex, "actor_hex": "00" * 16},
                      namespace="_cluster_actors")
        client.kv_put("ff" * 16, {"owner": doomed_hex, "state": "READY"},
                      namespace="_pgs")
        time.sleep(1.5)  # let a snapshot/WAL interval persist it all

        # the node and the head die together (rack loss)
        doomed.send_signal(signal.SIGKILL)
        head.send_signal(signal.SIGKILL)
        doomed.wait(timeout=30)
        head.wait(timeout=30)

        head2_log = os.path.join(tmp, "head2.log")
        head = _spawn(head_cmd + ["--restore"], open(head2_log, "w"), env=env)
        _wait_line(head2_log, "head up", proc=head)

        client = GcsClient(address, retry_window_s=10.0)
        # the doomed node's restored record is purged — either by the
        # reconcile grace sweep or by the head's own staleness detector,
        # whichever notices first (both are "existing death paths")
        _wait_until(
            lambda: client.kv_get(doomed_hex, namespace="_nodes") is None,
            timeout=30)
        # the reconcile sweep (grace 3s) releases what the node owned
        _wait_until(
            lambda: client.kv_get("drill/ghost",
                                  namespace="_cluster_actors") is None,
            timeout=30)
        _wait_until(
            lambda: (client.kv_get("ff" * 16, namespace="_pgs")
                     or {}).get("state") == "FAILED",
            timeout=30)
        # the survivor was NOT purged and NOT restarted
        info = client.kv_get(survivor_hex, namespace="_nodes")
        assert info and info["pid"] == survivor.pid
        assert survivor.poll() is None
    finally:
        _terminate(head, survivor, doomed)


@pytest.mark.slow
def test_kill_head_chaos_drill():
    """Capstone: chaos SIGKILLs the head from its own snapshot loop while
    (the same episode is bench-captured with metrics by
    `python bench_cluster.py --drill head_outage` -> BENCH_CLUSTER_r02);
    a writer keeps committing KV state and an agent keeps heartbeating.
    After --restore on the same port: every ACKNOWLEDGED write is still
    readable (zero acknowledged-write loss), the writer saw zero errors
    of any kind (its retry window spans the outage), a pre-restart writer
    is fenced by epoch, and the surviving agent re-registers without a
    process restart."""
    tmp = tempfile.mkdtemp(prefix="ray_tpu_chaos_head_")
    snap = os.path.join(tmp, "gcs.snap")
    port = _free_port()
    address = f"127.0.0.1:{port}"
    head_log = os.path.join(tmp, "head.log")
    agent_log = os.path.join(tmp, "agent.log")
    chaos_env = {**_ENV, "RAY_TPU_CHAOS":
                 "kill_head=1,delay_s=4.0,max_injections=1"}

    head_cmd = [
        sys.executable, "-m", "ray_tpu", "--no-tpu", "start", "--head",
        "--port", str(port), "--num-cpus", "1", "--snapshot-path", snap,
    ]
    head = _spawn(head_cmd, open(head_log, "w"), env=chaos_env)
    agent = None
    acked, errors = [], []
    stop_writer = threading.Event()

    def writer():
        # the retry window spans kill + restart: every put either acks or
        # retries invisibly — ANY surfaced exception fails the drill
        c = GcsClient(address, retry_window_s=60.0)
        c.adopt_epoch()  # exercise the re-adopt-on-fence recovery path
        i = 0
        while not stop_writer.is_set():
            try:
                if c.kv_put(f"w{i}", {"i": i}, namespace="drill"):
                    acked.append(i)
            except Exception as exc:  # noqa: BLE001 — the drill's verdict
                errors.append(exc)
            i += 1
            time.sleep(0.05)

    writer_thread = threading.Thread(target=writer, daemon=True)
    try:
        _wait_line(head_log, "head up", proc=head)
        agent = _spawn(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
             "--address", address, "--num-cpus", "1",
             "--resources", '{"pet": 1}'],
            open(agent_log, "w"))
        _wait_line(agent_log, "joined", proc=agent)

        # a zombie writer from the pre-kill era: pinned to the old epoch
        zombie = GcsClient(address, retry_window_s=30.0)
        pre_epoch = zombie.adopt_epoch()
        zombie.pin_epoch(pre_epoch)

        writer_thread.start()

        # chaos fires ~4s after the head armed it at init
        head.wait(timeout=60)
        assert head.returncode == 137, (
            f"head should die by chaos os._exit(137), got {head.returncode}")
        t_dead = time.monotonic()
        acked_at_death = len(acked)
        assert agent.poll() is None, "agent must survive the head kill"

        # restart WITHOUT the chaos env (a restarted head re-reading the
        # injection env must not be re-armed anyway, but the drill
        # measures recovery, not a crash loop)
        head2_log = os.path.join(tmp, "head2.log")
        head = _spawn(head_cmd + ["--restore"], open(head2_log, "w"))
        _wait_line(head2_log, "head up", proc=head)

        # recovery-time-to-ready: first successful write after restore
        probe = GcsClient(address, retry_window_s=30.0)
        _wait_until(lambda: probe.kv_get("w0", namespace="drill") is not None,
                    timeout=30)
        recovery_s = time.monotonic() - t_dead

        # traffic rode THROUGH the outage: more acks accumulated after
        # death than existed at death
        _wait_until(lambda: len(acked) > acked_at_death + 5, timeout=30)
        stop_writer.set()
        writer_thread.join(timeout=10)

        assert not errors, f"writer surfaced errors during the drill: {errors}"

        # zero acknowledged-write loss, spot-checked across the whole run
        # (writes acked pre-kill came back via snapshot+WAL; writes acked
        # post-restore are simply present)
        missing = [i for i in acked
                   if probe.kv_get(f"w{i}", namespace="drill") is None]
        assert not missing, f"acknowledged writes lost: {missing[:10]}"

        # the restart bumped the epoch and the zombie is fenced
        assert probe.head_info()["epoch"] > pre_epoch
        with pytest.raises(StaleEpochError):
            zombie.kv_put("zombie-write", 1, namespace="drill")

        # the agent re-registered (same process) and serves work again
        out = subprocess.run(
            [sys.executable, "-c", _OBSERVER, address, "pet", "1"],
            env=_ENV, capture_output=True, text=True, timeout=120,
        )
        assert "OBSERVER-OK" in out.stdout, out.stdout + out.stderr
        assert int(out.stdout.split("OBSERVER-OK")[1].strip()) == agent.pid

        assert recovery_s < 30, f"recovery took {recovery_s:.1f}s"
    finally:
        stop_writer.set()
        _terminate(head, agent)
