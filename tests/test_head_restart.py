"""Head restart from snapshot (round-4 verdict #10): kill the head,
restart it with --restore on the same port, and a surviving agent —
never restarted — re-registers via its retrying heartbeat loop, its
resources and parked state reappearing in the cluster view.

Reference: Redis-backed GCS restart (gcs_table_storage.h:275,
gcs_redis_failure_detector.h:35) where raylets outlive the GCS.
"""

import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
        "RAY_TPU_NODE_HEARTBEAT_S": "0.2", "RAY_TPU_NODE_STALE_S": "2.5",
        "RAY_TPU_GCS_SNAPSHOT_INTERVAL_S": "0.5"}

_OBSERVER = textwrap.dedent(
    """
    import sys, time
    import ray_tpu

    address, resource, want = sys.argv[1], sys.argv[2], float(sys.argv[3])
    ray_tpu.init(address=address, num_cpus=0, detect_accelerators=False)
    deadline = time.monotonic() + 60
    while ray_tpu.cluster_resources().get(resource, 0) < want:
        assert time.monotonic() < deadline, (
            f"never saw {resource}>={want}: {ray_tpu.cluster_resources()}"
        )
        time.sleep(0.2)

    @ray_tpu.remote(num_cpus=0, resources={resource: 1})
    def where():
        import os
        return os.getpid()

    pid = ray_tpu.get(where.remote(), timeout=60)
    ray_tpu.shutdown()
    print(f"OBSERVER-OK {pid}")
    """
)


def _spawn(cmd, log):
    return subprocess.Popen(
        cmd, env=_ENV, stdout=log, stderr=subprocess.STDOUT, text=True
    )


def _wait_line(path, needle, timeout=90, proc=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            break
        with open(path) as f:
            if needle in f.read():
                return
        time.sleep(0.2)
    with open(path) as f:
        raise AssertionError(f"never saw {needle!r} in:\n{f.read()}")


def test_head_restart_restores_surviving_agent():
    tmp = tempfile.mkdtemp(prefix="ray_tpu_headrestart_")
    snap = os.path.join(tmp, "gcs.snap")
    port = _free_port()
    address = f"127.0.0.1:{port}"
    head_log = os.path.join(tmp, "head.log")
    agent_log = os.path.join(tmp, "agent.log")

    head_cmd = [
        sys.executable, "-m", "ray_tpu", "--no-tpu", "start", "--head",
        "--port", str(port), "--num-cpus", "1", "--snapshot-path", snap,
    ]
    head = _spawn(head_cmd, open(head_log, "w"))
    agent = None
    try:
        _wait_line(head_log, "head up", proc=head)
        agent = _spawn(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
             "--address", address, "--num-cpus", "2",
             "--resources", '{"pet": 3}'],
            open(agent_log, "w"),
        )
        _wait_line(agent_log, "joined", proc=agent)

        # observer 1: the agent's resources are visible pre-kill
        out = subprocess.run(
            [sys.executable, "-c", _OBSERVER, address, "pet", "3"],
            env=_ENV, capture_output=True, text=True, timeout=120,
        )
        assert "OBSERVER-OK" in out.stdout, out.stdout + out.stderr
        agent_pid_1 = int(out.stdout.split("OBSERVER-OK")[1].strip())
        assert agent_pid_1 == agent.pid

        # give the snapshot loop a beat to persist the node table
        time.sleep(2.0)

        # kill the head hard; the agent keeps running (heartbeats warn)
        head.send_signal(signal.SIGKILL)
        head.wait(timeout=30)
        time.sleep(1.0)
        assert agent.poll() is None, "agent must survive head death"

        # restart the head from the snapshot, same port
        head = _spawn(head_cmd + ["--restore"], open(head_log, "a"))
        _wait_line(head_log, "head up", proc=head)

        # observer 2: the surviving agent (same pid!) re-registered and
        # still executes work — no agent restart happened
        out = subprocess.run(
            [sys.executable, "-c", _OBSERVER, address, "pet", "3"],
            env=_ENV, capture_output=True, text=True, timeout=120,
        )
        assert "OBSERVER-OK" in out.stdout, out.stdout + out.stderr
        agent_pid_2 = int(out.stdout.split("OBSERVER-OK")[1].strip())
        assert agent_pid_2 == agent.pid == agent_pid_1
    finally:
        for proc in (head, agent):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
