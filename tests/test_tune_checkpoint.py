"""Tune trial checkpointing, failure retry, Tuner.restore, and PBT
(reference: tune/execution/experiment_state.py, tune/schedulers/pbt.py:221)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import session as train_session
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.schedulers import Exploit, PopulationBasedTraining


@pytest.fixture(autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield
    ray_tpu.shutdown()


def _counting_trainable(config):
    """Counts up, checkpointing each step; resumes where it left off and
    crashes once at step 3 unless it already restarted."""
    ckpt = train_session.get_checkpoint()
    start = ckpt["step"] + 1 if ckpt else 0
    for step in range(start, 6):
        if step == 3 and ckpt is None:
            raise RuntimeError("injected trial crash")
        train_session.report(
            {"step": step, "resumed": ckpt is not None},
            checkpoint={"step": step},
        )


def test_trial_crash_resumes_from_checkpoint(tmp_path):
    tuner = Tuner(
        _counting_trainable,
        param_space={"x": [1]},
        tune_config=TuneConfig(
            metric="step", mode="max", max_failures=1,
            storage_path=str(tmp_path),
        ),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.status.value == "TERMINATED"
    # crashed at step 3, resumed from ckpt step 2, continued through 5
    assert best.last_result["step"] == 5
    assert best.last_result["resumed"] is True
    assert best.num_failures == 1
    steps = [r["step"] for r in best.history]
    assert steps[-3:] == [3, 4, 5]


def test_no_retry_budget_errors_out(tmp_path):
    def always_crash(config):
        raise RuntimeError("nope")

    grid = Tuner(
        always_crash,
        param_space={"x": [1]},
        tune_config=TuneConfig(storage_path=str(tmp_path)),
    ).fit()
    trial = list(grid)[0]
    assert trial.status.value == "ERRORED"
    assert "nope" in trial.error


def test_tuner_restore_skips_finished_reruns_unfinished(tmp_path):
    calls_file = tmp_path / "calls.txt"

    def trainable(config):
        with open(calls_file, "a") as f:
            f.write(f"{config['idx']}\n")
        if config["idx"] == 1 and not train_session.get_checkpoint():
            # first run of trial 1 dies without finishing
            train_session.report({"score": 0}, checkpoint={"seen": True})
            raise RuntimeError("die once")
        train_session.report({"score": config["idx"] * 10})

    cfg = TuneConfig(metric="score", mode="max", storage_path=str(tmp_path))
    grid = Tuner(
        trainable, param_space={"idx": {"grid_search": [0, 1]}}, tune_config=cfg
    ).fit()
    statuses = {t.trial_id: t.status.value for t in grid}
    assert statuses["trial_00000"] == "TERMINATED"
    assert statuses["trial_00001"] == "ERRORED"

    restored = Tuner.restore(str(tmp_path), trainable)
    grid2 = restored.fit()
    statuses = {t.trial_id: t.status.value for t in grid2}
    assert statuses["trial_00001"] == "TERMINATED"  # resumed via checkpoint
    runs = [int(x) for x in calls_file.read_text().split()]
    # trial 0 ran exactly once: restore did not re-run the finished trial
    assert runs.count(0) == 1


def test_pbt_exploits_and_mutates(tmp_path):
    """Weak trials must adopt (and perturb) strong trials' configs, and
    resume from the donor's checkpoint."""

    def trainable(config):
        import time as _time

        ckpt = train_session.get_checkpoint() or {"acc": 0.0, "steps": 0}
        acc, start = ckpt["acc"], ckpt["steps"]
        for step in range(start, start + 12):
            acc += config["lr"]  # higher lr == strictly better here
            train_session.report(
                {"acc": acc, "lr": config["lr"]},
                checkpoint={"acc": acc, "steps": step + 1},
            )
            _time.sleep(0.05)  # let controller polls interleave the population

    pbt = PopulationBasedTraining(
        metric="acc",
        mode="max",
        perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 0.5, 1.0, 2.0]},
        quantile_fraction=0.34,
        seed=7,
    )
    grid = Tuner(
        trainable,
        param_space={"lr": {"grid_search": [0.1, 0.5, 2.0]}},
        tune_config=TuneConfig(
            metric="acc", mode="max", scheduler=pbt, max_concurrent=3,
            storage_path=str(tmp_path),
        ),
    ).fit()
    assert pbt.num_exploits >= 1
    exploited = [t for t in grid if t.num_exploits > 0]
    assert exploited, "no trial ever exploited"
    # the weakest config must not still be running lr=0.1 at the end
    for t in exploited:
        assert t.config["lr"] != 0.1
        # exploited trials carried donor progress: their reported acc must
        # exceed anything reachable alone from scratch with lr=0.1
        assert t.last_result["acc"] > 0.1 * 12 + 1e-9


def test_pbt_scheduler_unit():
    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": [1, 2, 4]}, quantile_fraction=0.5, seed=0,
    )
    pbt.on_trial_config("a", {"lr": 4})
    pbt.on_trial_config("b", {"lr": 1})
    assert pbt.on_result("a", {"score": 10, "training_iteration": 2}) == "CONTINUE"
    verdict = pbt.on_result("b", {"score": 1, "training_iteration": 2})
    assert isinstance(verdict, Exploit)
    assert verdict.donor_trial == "a"
    assert "lr" in verdict.new_config


# ------------------------------------------------- tune x train integration


def _gang_epoch_trainable(config):
    """A Tune trial that drives a REAL TrainController gang per epoch,
    checkpointing through the tune session and crashing once mid-trial
    (VERDICT r3 weak #8: Tuner -> TrainController with a mid-trial
    checkpointed restore)."""
    from ray_tpu.train import RunConfig, ScalingConfig, Trainer

    ckpt = train_session.get_checkpoint()
    start = ckpt["epoch"] + 1 if ckpt else 0
    for epoch in range(start, 4):
        if epoch == 2 and ckpt is None:
            raise RuntimeError("injected mid-training crash")

        def loop(cfg, _epoch=epoch):
            from ray_tpu import train

            for i in range(2):
                train.report({"inner_step": i, "epoch": _epoch})

        result = Trainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name=f"inner-{config['lr']}-{epoch}"),
            train_loop_config={},
        ).fit()
        assert result.status.value == "FINISHED", result.error
        assert result.metrics["epoch"] == epoch
        train_session.report(
            {"epoch": epoch, "loss": 1.0 / (epoch + 1) * config["lr"],
             "resumed": ckpt is not None},
            checkpoint={"epoch": epoch},
        )


def test_tuner_drives_train_controller_with_restore(tmp_path):
    tuner = Tuner(
        _gang_epoch_trainable,
        param_space={"lr": tune.grid_search([1.0, 2.0])},
        tune_config=TuneConfig(
            metric="loss", mode="min", max_failures=1,
            storage_path=str(tmp_path),
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    for trial in grid:
        assert trial.status.value == "TERMINATED", trial.error
        assert trial.last_result["epoch"] == 3
        assert trial.last_result["resumed"] is True  # every trial crashed once
        assert trial.num_failures == 1
    best = grid.get_best_result()
    assert best.config["lr"] == 1.0  # lower lr -> lower synthetic loss
