"""OpenAI-compatible serving surface (round-4 verdict #6): schema
conformance for /v1/completions and /v1/chat/completions including
usage accounting and SSE streamed chunks ending in [DONE].

Reference: build_openai_app (serve/llm/__init__.py in the reference).
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def endpoint():
    ray_tpu.init(num_cpus=4, detect_accelerators=False)
    from ray_tpu.serve.llm import serve_openai

    frontend = serve_openai(model="gpt2-tiny", paged=True, max_slots=4)
    yield f"http://127.0.0.1:{frontend.port}"
    frontend.stop()
    serve.shutdown()
    ray_tpu.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(req, timeout=300)


def test_models_route(endpoint):
    with urllib.request.urlopen(endpoint + "/v1/models", timeout=60) as r:
        body = json.loads(r.read())
    assert body["object"] == "list"
    assert body["data"][0]["id"] == "gpt2-tiny"
    assert body["data"][0]["object"] == "model"


def test_completions_schema(endpoint):
    with _post(endpoint + "/v1/completions", {
        "model": "gpt2-tiny", "prompt": "hello tpu", "max_tokens": 8,
        "temperature": 0.0,
    }) as r:
        body = json.loads(r.read())
    assert body["object"] == "text_completion"
    assert body["id"].startswith("cmpl-")
    assert body["model"] == "gpt2-tiny"
    (choice,) = body["choices"]
    assert choice["index"] == 0
    assert isinstance(choice["text"], str)
    assert choice["finish_reason"] in ("stop", "length")
    usage = body["usage"]
    assert usage["prompt_tokens"] == len("hello tpu".encode())
    assert usage["completion_tokens"] == 8
    assert usage["total_tokens"] == usage["prompt_tokens"] + 8


def test_completions_token_array_prompt(endpoint):
    """OpenAI's token-array prompt form bypasses the byte tokenizer."""
    with _post(endpoint + "/v1/completions", {
        "model": "gpt2-tiny", "prompt": [1, 2, 3, 4], "max_tokens": 4,
    }) as r:
        body = json.loads(r.read())
    assert body["usage"]["prompt_tokens"] == 4
    assert body["usage"]["completion_tokens"] == 4


def test_chat_completions_schema(endpoint):
    with _post(endpoint + "/v1/chat/completions", {
        "model": "gpt2-tiny",
        "messages": [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"},
        ],
        "max_tokens": 6, "temperature": 0.0,
    }) as r:
        body = json.loads(r.read())
    assert body["object"] == "chat.completion"
    assert body["id"].startswith("chatcmpl-")
    (choice,) = body["choices"]
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert body["usage"]["completion_tokens"] == 6


def test_streaming_sse(endpoint):
    req = {
        "model": "gpt2-tiny",
        "messages": [{"role": "user", "content": "stream!"}],
        "max_tokens": 5, "temperature": 0.0,
    }
    with _post(endpoint + "/v1/chat/completions", req) as r:
        dense = json.loads(r.read())
    with _post(endpoint + "/v1/chat/completions",
               {**req, "stream": True}) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    frames = [
        line[len("data: "):]
        for line in raw.split("\n") if line.startswith("data: ")
    ]
    assert frames[-1] == "[DONE]"
    chunks = [json.loads(f) for f in frames[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    deltas = [
        c["choices"][0]["delta"].get("content", "") for c in chunks
    ]
    # the concatenated stream equals the non-streamed completion (the
    # incremental UTF-8 decoder may merge or hold back byte-tokens, so
    # chunk COUNT is not 1:1 with tokens — the TEXT must match exactly)
    assert "".join(deltas) == dense["choices"][0]["message"]["content"]
    # max_tokens reached -> finish_reason "length", matching non-stream
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    assert dense["choices"][0]["finish_reason"] == "length"
    assert chunks[-1]["usage"]["completion_tokens"] == 5


def test_error_schema(endpoint):
    try:
        _post(endpoint + "/v1/completions", {
            "model": "no-such-model", "prompt": "x",
        })
        raise AssertionError("expected HTTP error")
    except urllib.error.HTTPError as e:
        body = json.loads(e.read())
        assert e.code == 404
        assert body["error"]["type"] == "invalid_request_error"


def test_multibyte_stop_sequence_truncates(endpoint):
    """Multi-byte stop strings are honored (engine-side tail match +
    OpenAI-style truncation), not rejected as they were before."""
    req = {
        "model": "gpt2-tiny", "prompt": "hello tpu", "max_tokens": 24,
        "temperature": 0.0,
    }
    with _post(endpoint + "/v1/completions", req) as r:
        base = json.loads(r.read())
    text = base["choices"][0]["text"]
    assert len(text) >= 4  # greedy decode of 24 byte-tokens
    stop = text[1:3]  # a 2-char (multi-byte) substring of the output
    with _post(endpoint + "/v1/completions", {**req, "stop": stop}) as r:
        body = json.loads(r.read())
    choice = body["choices"][0]
    # greedy decode is deterministic: the stopped run is the same text
    # truncated BEFORE the first stop occurrence, finish_reason "stop"
    assert choice["text"] == text[: text.find(stop)]
    assert stop not in choice["text"]
    assert choice["finish_reason"] == "stop"


def test_multibyte_stop_sequence_streaming(endpoint):
    req = {
        "model": "gpt2-tiny", "prompt": "hello tpu", "max_tokens": 24,
        "temperature": 0.0,
    }
    with _post(endpoint + "/v1/completions", req) as r:
        base = json.loads(r.read())
    text = base["choices"][0]["text"]
    stop = text[1:3]
    with _post(endpoint + "/v1/completions",
               {**req, "stop": stop, "stream": True}) as r:
        raw = r.read().decode()
    frames = [
        line[len("data: "):]
        for line in raw.split("\n") if line.startswith("data: ")
    ]
    assert frames[-1] == "[DONE]"
    chunks = [json.loads(f) for f in frames[:-1]]
    streamed = "".join(c["choices"][0].get("text", "") for c in chunks)
    # the held-back scanner never leaks the stop string onto the wire
    assert streamed == text[: text.find(stop)]
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
