"""Agent-side admission control and result-delivery recovery
(reference models: the raylet granting worker leases against its OWN
resource ledger, src/ray/raylet/node_manager.cc:2000
HandleRequestWorkerLease, and the core worker re-resolving lost
completions instead of hanging).

These are the round-4 verdict's "two drivers sharing one cluster" and
"owner partitioned past the delivery budget" scenarios — both were
design gaps, not just untested paths.
"""

import os
import subprocess
import sys
import tempfile
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def _max_overlap(log_path):
    """Max number of intervals simultaneously open in a 'S ns'/'E ns'
    event log written by the flood tasks."""
    events = []
    with open(log_path) as f:
        for line in f:
            kind, ns = line.split()
            events.append((int(ns), 1 if kind == "S" else -1))
    events.sort()
    cur = peak = 0
    for _, delta in events:
        cur += delta
        peak = max(peak, cur)
    return peak



def _wait_for(path, timeout=90):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        assert time.monotonic() < deadline, f"never appeared: {path}"
        time.sleep(0.05)


_SECOND_DRIVER = textwrap.dedent(
    """
    import os, sys, time
    import ray_tpu

    address, log_path, n = sys.argv[1], sys.argv[2], int(sys.argv[3])
    ready_path = sys.argv[4]
    ray_tpu.init(address=address, num_cpus=0, detect_accelerators=False)
    deadline = time.monotonic() + 60
    while ray_tpu.cluster_resources().get("sink", 0) < 2:
        assert time.monotonic() < deadline, "sink node never discovered"
        time.sleep(0.1)
    open(ready_path, "w").write("ready")  # both drivers flood together

    @ray_tpu.remote(num_cpus=0, resources={"sink": 1})
    def flood(log_path, hold_s):
        import os as _os, time as _time
        fd = _os.open(log_path, _os.O_WRONLY | _os.O_APPEND)
        try:
            _os.write(fd, f"S {_time.monotonic_ns()}\\n".encode())
            _time.sleep(hold_s)
            _os.write(fd, f"E {_time.monotonic_ns()}\\n".encode())
        finally:
            _os.close(fd)
        return _os.getpid()

    pids = ray_tpu.get([flood.remote(log_path, 0.3) for _ in range(n)],
                       timeout=180)
    assert len(pids) == n
    ray_tpu.shutdown()
    print("SECOND-DRIVER-OK")
    """
)


@pytest.fixture
def sink_cluster():
    """Head (1 CPU) + one agent holding the only 'sink' resources (2):
    every sink task in the whole cluster must execute on that agent."""
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, resources={"sink": 2},
               system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(2)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def test_two_driver_flood_respects_agent_ledger(sink_cluster):
    """Two drivers flooding the same agent: total concurrent executions
    never exceed the agent's sink capacity (2) — the agent's OWN ledger
    admits, not the drivers' optimistic views."""
    fd, log_path = tempfile.mkstemp(prefix="ray_tpu_flood_", suffix=".log")
    os.close(fd)
    n_each = 6

    @ray_tpu.remote(num_cpus=0, resources={"sink": 1})
    def flood(log_path, hold_s):
        # append start/end markers with O_APPEND atomic writes
        fd = os.open(log_path, os.O_WRONLY | os.O_APPEND)
        try:
            os.write(fd, f"S {time.monotonic_ns()}\n".encode())
            time.sleep(hold_s)
            os.write(fd, f"E {time.monotonic_ns()}\n".encode())
        finally:
            os.close(fd)
        return os.getpid()

    ready_path = log_path + ".ready"
    second = subprocess.Popen(
        [sys.executable, "-c", _SECOND_DRIVER,
         sink_cluster.address, log_path, str(n_each), ready_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        _wait_for(ready_path)
        refs = [flood.remote(log_path, 0.3) for _ in range(n_each)]
        pids = ray_tpu.get(refs, timeout=180)
        out, _ = second.communicate(timeout=180)
    finally:
        if second.poll() is None:
            second.kill()
    assert "SECOND-DRIVER-OK" in out, f"second driver failed:\n{out}"
    assert len(pids) == n_each

    events = sum(1 for _ in open(log_path))
    assert events == 2 * 2 * n_each, f"lost log events: {events}"
    peak = _max_overlap(log_path)
    assert peak <= 2, (
        f"agent ran {peak} sink tasks concurrently with capacity 2 — "
        f"admission control failed"
    )
    os.unlink(log_path)


_DRIP_DRIVER = textwrap.dedent(
    """
    import sys, time
    import ray_tpu

    address, n, ready_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    ray_tpu.init(address=address, num_cpus=0, detect_accelerators=False)
    deadline = time.monotonic() + 60
    while ray_tpu.cluster_resources().get("drip", 0) < 2:
        assert time.monotonic() < deadline, "drip node never discovered"
        time.sleep(0.1)
    open(ready_path, "w").write("ready")  # both drivers flood together

    @ray_tpu.remote(num_cpus=0, resources={"drip": 1})
    def drip(i):
        import time as _time
        _time.sleep(0.15)
        return i

    outs = ray_tpu.get([drip.remote(i) for i in range(n)], timeout=180)
    assert sorted(outs) == list(range(n))
    ray_tpu.shutdown()
    print("DRIP-DRIVER-OK")
    """
)


def test_admission_queue_overflow_bounces_and_completes(sink_cluster):
    """Two drivers into a capacity-2 agent with a 1-deep admission
    queue: overflowing dispatches bounce back ("busy") to their owner's
    scheduler, which requeues — everything still completes exactly
    once, and the agent records the bounces. (Each driver keeps up
    to 2 dispatches in flight by its own view, so up to 4 arrive against
    2 ledger slots + 1 queue slot.)"""
    sink_cluster.add_node(
        num_cpus=1, resources={"drip": 2},
        system_config={"node_heartbeat_s": 0.2, "agent_admission_queue": 1},
    )
    sink_cluster.wait_for_nodes(3)
    n_each = 6

    @ray_tpu.remote(num_cpus=0, resources={"drip": 1})
    def drip(i):
        time.sleep(0.15)
        return i

    fd, ready_path = tempfile.mkstemp(prefix="ray_tpu_drip_")
    os.close(fd)
    os.unlink(ready_path)
    second = subprocess.Popen(
        [sys.executable, "-c", _DRIP_DRIVER, sink_cluster.address,
         str(n_each), ready_path],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        _wait_for(ready_path)
        outs = ray_tpu.get([drip.remote(i) for i in range(n_each)],
                           timeout=180)
        out, _ = second.communicate(timeout=180)
    finally:
        if second.poll() is None:
            second.kill()
    assert "DRIP-DRIVER-OK" in out, f"second driver failed:\n{out}"
    assert sorted(outs) == list(range(n_each))

    # the agent itself counted at least one bounce (capacity 1 + queue 1
    # cannot absorb two drivers' concurrent dispatches)
    @ray_tpu.remote(num_cpus=0, resources={"drip": 1})
    def agent_stats():
        from ray_tpu.core.runtime import get_runtime

        return dict(get_runtime().cluster.agent_stats)

    stats = ray_tpu.get(agent_stats.remote(), timeout=60)
    assert stats["bounced"] >= 1, f"no bounces recorded: {stats}"
    assert stats["queued"] >= 1, f"nothing ever queued: {stats}"


def test_parked_result_recovery_after_owner_outage(sink_cluster):
    """The owner's transfer/control server goes dark past the agent's
    delivery budget; the agent PARKS the completion and the owner's
    poll loop reclaims it — get() completes instead of hanging forever
    (round-4 verdict Weak#2)."""
    from ray_tpu.core.config import cfg
    from ray_tpu.core.rpc import RpcServer

    cfg.set(pending_task_poll_s=2.0)
    # a dedicated agent with a tiny delivery budget so it parks fast
    sink_cluster.add_node(
        num_cpus=1, resources={"park": 1},
        system_config={
            "node_heartbeat_s": 0.2,
            "result_delivery_attempts": 2,
        },
    )
    sink_cluster.wait_for_nodes(3)

    @ray_tpu.remote(num_cpus=0, resources={"park": 1})
    def compute():
        time.sleep(1.0)
        return 41 + 1

    ctx = sink_cluster.runtime.cluster
    ref = compute.remote()
    time.sleep(0.3)  # dispatch reaches the agent
    # Owner goes dark: stop the node server (heartbeats ride the GCS
    # server, which stays up — the node is alive, just unreachable).
    inner = ctx.server._server
    host, port = inner.address
    inner.stop()
    time.sleep(4.0)  # outlives 2 delivery attempts -> parked
    # owner comes back on the SAME address with the same handlers
    ctx.server._server = RpcServer(
        inner.handlers, host=host, port=port, token=sink_cluster.token
    )
    assert ray_tpu.get(ref, timeout=60) == 42


def test_foreign_get_gives_up_without_location():
    """Standalone-store regression (round-4 advisor): a cluster-mode
    get() on a ref whose producer never registers a location must end in
    ObjectLostError after the bounded directory poll, not hang."""
    from ray_tpu.core.config import cfg
    from ray_tpu.core.exceptions import ObjectLostError
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_store import ObjectStore

    cfg.set(foreign_locate_max_s=0.4)
    try:
        store = ObjectStore()
        store.set_cluster_hooks(
            fetch_remote=lambda oid, addr: None, locate=lambda oid: None
        )
        t0 = time.monotonic()
        with pytest.raises(ObjectLostError):
            store.get(ObjectID.from_random(), timeout=None)
        assert time.monotonic() - t0 < 5.0
    finally:
        cfg.reset("foreign_locate_max_s")
