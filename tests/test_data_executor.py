"""PR 12 streaming-executor pressure paths: byte budget, spill riding,
prefetch off-by-one regression, locality routing, split fairness.

These are the driver-measured acceptance behaviours from the issue:
ingest under a tiny store must SPILL (not deadlock, not OOM), the
in-flight window must respect its byte budget, and constrained results
must equal unconstrained ones exactly.
"""

import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.dataset import DataContext
from ray_tpu.data.executor import StreamStats, node_holding


@pytest.fixture
def runtime():
    rt = ray_tpu.init(num_cpus=8, num_nodes=4, detect_accelerators=False)
    yield rt
    ray_tpu.shutdown()


def _big_block_ds(num_blocks: int = 12) -> rd.Dataset:
    # 128 KiB blocks: over the 100 KiB inline cutoff, so sealed blocks
    # are HOST-tier spill candidates under store pressure
    rng = np.random.default_rng(11)
    return rd.from_numpy(
        {"tokens": rng.integers(0, 255, num_blocks * 32768).astype(np.int32)},
        num_blocks=num_blocks,
    ).map_batches(lambda b: {"tokens": (b["tokens"] * 3 + 1) % 251})


def test_byte_budget_spill_and_exactness():
    """Tiny store + tiny in-flight budget: the pipeline must spill
    (spilled_bytes > 0), never exceed its byte budget, and produce
    exactly the rows an unconstrained run produces."""
    ray_tpu.init(num_cpus=4, num_nodes=2, detect_accelerators=False)
    try:
        want = sorted(
            int(r) for b in _big_block_ds().iter_blocks() for r in b["tokens"]
        )
    finally:
        ray_tpu.shutdown()

    budget = 640 << 10
    with tempfile.TemporaryDirectory() as tmp:
        ray_tpu.init(num_cpus=4, num_nodes=2, detect_accelerators=False,
                     object_store_capacity=256 << 10, spill_dir=tmp)
        ctx = DataContext.get_current()
        saved = (ctx.target_inflight_bytes, ctx.backpressure_max_stall_s)
        ctx.target_inflight_bytes = budget
        ctx.backpressure_max_stall_s = 0.5
        try:
            ds = _big_block_ds()
            got = sorted(
                int(r) for b in ds.iter_blocks() for r in b["tokens"]
            )
            stats = ds.stats()
        finally:
            ctx.target_inflight_bytes, ctx.backpressure_max_stall_s = saved
            ray_tpu.shutdown()

    assert got == want
    assert stats["spilled_bytes"] > 0, "tiny store must force spilling"
    assert stats["max_inflight_bytes"] <= budget, (
        f"in-flight {stats['max_inflight_bytes']} exceeded budget {budget}"
    )


def test_unconstrained_run_does_not_stall(runtime):
    ds = rd.range(500, num_blocks=10).map(lambda r: int(r) + 1)
    assert sorted(int(r) for r in ds.take(1000)) == list(range(1, 501))
    stats = ds.stats()
    assert stats["backpressure_stall_s"] == 0.0
    assert stats["blocks_consumed"] == 10


def test_jax_batch_stream_yields_after_first_batch():
    """Off-by-one regression: the first batch must be yielded after ONE
    upstream pull, not after the whole prefetch window fills (a slow
    producer would otherwise delay time-to-first-step by `prefetch`
    batches)."""
    from ray_tpu.data.dataset import _jax_batch_stream

    pulled = []

    def producer():
        for i in range(8):
            pulled.append(i)
            yield {"x": np.full(4, i, dtype=np.int32)}

    stream = _jax_batch_stream(producer(), prefetch=4, sharding=None,
                               columns=None)
    first = next(stream)
    assert np.asarray(first["x"]).tolist() == [0, 0, 0, 0]
    assert len(pulled) == 1, (
        f"first yield pulled {len(pulled)} upstream batches, expected 1"
    )
    rest = list(stream)
    assert len(rest) == 7
    assert len(pulled) == 8


def test_locality_hint_places_on_hinted_node(runtime):
    """locality_hint is honoured as a soft preference: on an idle
    cluster, hinted tasks land on the hinted node."""
    from ray_tpu.core.ids import NodeID

    rt = ray_tpu.api._runtime()
    target = rt.scheduler.nodes()[-1].node_id

    @ray_tpu.remote
    def where():
        return True

    refs = [
        where.options(locality_hint=NodeID(target.hex())).remote()
        for _ in range(5)
    ]
    ray_tpu.get(refs, timeout=30)
    nodes = [
        ev["node"] for ev in rt.task_events()
        if ev["task_id"] in {r.object_id.task_id().hex() for r in refs}
    ]
    assert nodes and all(n == target.hex() for n in nodes)


def test_node_holding_resolves_producer(runtime):
    ds = rd.range(40, num_blocks=4)
    refs = list(ds.iter_block_refs())
    ray_tpu.get(refs, timeout=30)  # placement is recorded at completion
    rt = ray_tpu.api._runtime()
    known = {n.node_id.hex() for n in rt.scheduler.nodes()}
    holders = [node_holding(ref) for ref in refs]
    assert all(h is None or h in known for h in holders)
    assert any(h is not None for h in holders)


def test_local_pipeline_locality_hit_rate(runtime):
    """The acceptance bar: >= 0.8 of map tasks run on the node holding
    their input block (in-process nodes are all feasible, so the soft
    preference should always win)."""
    ds = rd.range(1000, num_blocks=10).map_batches(
        lambda b: {"item": b["item"] * 2}
    )
    assert ds.count() == 1000
    stats = ds.stats()
    assert stats["locality_total"] > 0
    assert stats["locality_hit_rate"] >= 0.8


def test_streaming_split_skip_ahead_opt_in_past_stalled_consumer(runtime):
    """skip_ahead=True (independent consumers): with one split never
    consumed, the other split must still receive blocks instead of the
    pump deadlocking on the stalled split's bounded buffer — at the
    documented cost of unequal shares."""
    ds = rd.range(600, num_blocks=12)
    left, right = ds.streaming_split(2, skip_ahead=True)
    right_rows = [int(r) for r in right.iter_rows()]
    # skip-ahead hands the stalled split's overflow to the live one:
    # strictly more than an even share, and the pump never deadlocks
    assert len(right_rows) > 300
    left_rows = [int(r) for r in left.iter_rows()]
    assert sorted(left_rows + right_rows) == list(range(600))


def _consume_splits(splits):
    """Drain every split on its own thread (gang-shaped consumption)."""
    import threading

    results = [[] for _ in splits]

    def consume(i):
        results[i] = [int(r) for r in splits[i].iter_rows()]

    threads = [
        threading.Thread(target=consume, args=(i,))
        for i in range(len(splits))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "split consumer hung"
    return results


def test_streaming_split_default_is_deterministic_round_robin(runtime):
    """The gang-feed invariant: with the default strict round-robin,
    every split receives EXACTLY its i, i+k, i+2k, … blocks regardless
    of consumer pacing — so dp ranks can never disagree on their share
    because a sibling skipped ahead."""
    ds = rd.range(600, num_blocks=12)  # 12 blocks of 50 > 2*cap(4)
    rows0, rows1 = _consume_splits(ds.streaming_split(2))
    assert len(rows0) == len(rows1) == 300
    # blocks 0,2,4,… to split 0; 1,3,5,… to split 1 — deterministic
    assert rows0 == sorted(rows0)
    assert rows1 == sorted(rows1)
    assert sorted(rows0 + rows1) == list(range(600))


def test_streaming_split_equal_drops_partial_round(runtime):
    """equal=True: only complete rounds are delivered, so every split
    ends with the same block count even when the block count does not
    divide by k (the trailing partial round is dropped)."""
    ds = rd.range(130, num_blocks=13)  # 13 blocks of 10 rows, k=2
    rows0, rows1 = _consume_splits(ds.streaming_split(2, equal=True))
    assert len(rows0) == len(rows1) == 60  # 6 full rounds; block 13 dropped
    with pytest.raises(ValueError):
        ds.streaming_split(2, equal=True, skip_ahead=True)


def test_streaming_split_close_stops_pump(runtime):
    """The gang-restart leak path: closing one iterator tears down the
    shared execution — the pump thread exits (instead of spinning in
    push()/cv.wait forever) and every sibling sees end-of-stream."""
    import threading
    import time as _time

    ds = rd.range(1200, num_blocks=24)
    left, right = ds.streaming_split(2)
    # pull one block so the pump is alive and blocked on full buffers
    next(iter(left.iter_blocks()))
    assert any(
        t.name == "data-split-pump" and t.is_alive()
        for t in threading.enumerate()
    )
    left.close()
    deadline = _time.monotonic() + 10
    while _time.monotonic() < deadline:
        if not any(
            t.name == "data-split-pump" and t.is_alive()
            for t in threading.enumerate()
        ):
            break
        _time.sleep(0.05)
    else:
        raise AssertionError("split pump thread did not exit after close()")
    # siblings drain to end-of-stream instead of hanging
    assert list(right.iter_blocks()) == []


def test_gang_feed_drop_last_defaults_aligned(runtime):
    """drop_last defaults are consistent across the two iterator types
    (False for iter_batches, matching Dataset.iter_batches, so
    streaming_split consumers do not silently lose tail rows) while the
    gang-feed jax paths both default True so every rank sees the same
    number of steps regardless of how the tail rows split."""
    import inspect

    from ray_tpu.data.dataset import DataIterator, Dataset

    assert (inspect.signature(DataIterator.iter_batches)
            .parameters["drop_last"].default is False)
    assert (inspect.signature(Dataset.iter_batches)
            .parameters["drop_last"].default is False)
    assert (inspect.signature(DataIterator.iter_jax_batches)
            .parameters["drop_last"].default is True)
    assert (inspect.signature(Dataset.iter_jax_batches)
            .parameters["drop_last"].default is True)

    ds = rd.range(103, num_blocks=4)  # ragged tail: 103 % 10 != 0
    it = ds.streaming_split(1)[0]
    batches = list(it.iter_batches(10, drop_last=True))  # the gang path
    assert all(len(b["item"]) == 10 for b in batches)
    assert len(batches) == 10  # the 3-row tail is dropped
    it2 = ds.streaming_split(1)[0]
    tail = list(it2.iter_batches(10))  # default keeps the partial tail
    assert len(tail) == 11 and len(tail[-1]["item"]) == 3


def test_stream_stats_snapshot_keys(runtime):
    ds = rd.range(100, num_blocks=4).map(lambda r: int(r))
    ds.count()
    stats = ds.stats()
    for key in ("blocks_produced", "bytes_produced", "blocks_consumed",
                "bytes_consumed", "backpressure_stall_s",
                "max_inflight_bytes", "locality_hit_rate", "spilled_bytes",
                "reexecuted_blocks"):
        assert key in stats, key


def test_stream_stats_byte_budget_recorded():
    stats = StreamStats(byte_budget=1234)
    assert stats.snapshot()["byte_budget"] == 1234
