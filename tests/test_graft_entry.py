"""The driver contract: entry() compiles; dryrun_multichip runs on 8 devices."""

import sys

sys.path.insert(0, "/root/repo")

import jax
import pytest


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_entry_compiles_tiny():
    # entry() uses the 124M flagship — too slow for CPU CI, so check the
    # factorization helper + that entry is importable and well-formed.
    import __graft_entry__ as g

    spec = g._mesh_spec_for(8)
    assert spec.num_devices == 8
    spec1 = g._mesh_spec_for(1)
    assert spec1.num_devices == 1
    assert callable(g.entry)
