"""ViT encoder + CLIP dual tower."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.vit import (
    clip_forward,
    clip_loss,
    clip_tiny,
    forward,
    init_clip_params,
    init_params,
    logical_axes,
    patchify,
    vit_tiny,
)


def test_patchify_roundtrip_values():
    images = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
    patches = patchify(images, 4)
    assert patches.shape == (2, 4, 48)
    # first patch = top-left 4x4 block
    np.testing.assert_array_equal(
        np.asarray(patches[0, 0]), np.asarray(images[0, :4, :4, :]).reshape(-1)
    )


def test_vit_forward_and_not_order_invariant():
    config = vit_tiny()
    params = init_params(config, jax.random.PRNGKey(0))
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    out = forward(params, images, config)
    assert out.shape == (2, 10)
    assert np.isfinite(np.asarray(out)).all()
    # pos embeddings break permutation invariance: flipped image ≠ original
    flipped = images[:, ::-1]
    out2 = forward(params, flipped, config)
    assert not np.allclose(np.asarray(out), np.asarray(out2), atol=1e-4)


def test_vit_axes_tree_matches():
    config = vit_tiny()
    params = init_params(config, jax.random.PRNGKey(0))
    axes = logical_axes(config)
    p_paths = {
        tuple(str(k) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    a_paths = {
        tuple(str(k) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(
            axes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
    }
    assert p_paths == a_paths


def test_vit_grad_flows():
    config = vit_tiny()
    params = init_params(config, jax.random.PRNGKey(0))
    images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    labels = jnp.array([1, 7])

    def loss(p):
        from ray_tpu.ops import cross_entropy_loss

        return cross_entropy_loss(forward(p, images, config), labels)[0]

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))
    assert float(jnp.linalg.norm(g["patch_proj"])) > 0


def test_clip_forward_shapes_and_norms():
    config = clip_tiny()
    params = init_clip_params(config, jax.random.PRNGKey(0))
    images = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 12), 0, 256)
    lengths = jnp.array([12, 8, 5, 12])
    img, txt, scale = clip_forward(params, images, tokens, lengths, config)
    assert img.shape == (4, 32) and txt.shape == (4, 32)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(img), axis=-1), 1.0, rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(txt), axis=-1), 1.0, rtol=1e-5)
    assert float(scale) > 0


def test_clip_contrastive_training_aligns_pairs():
    import optax

    config = clip_tiny()
    params = init_clip_params(config, jax.random.PRNGKey(0))
    images = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 12), 0, 256)
    lengths = jnp.full((4,), 12)

    # 5e-3 over more steps: 1e-2 overshoots this toy problem into a
    # text-embedding collapse on some optimization trajectories (seen
    # when XLA fusion-order drift nudged the path) — at 5e-3 the pairs
    # align to loss ~0 across seeds
    opt = optax.adam(5e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: clip_loss(p, images, tokens, lengths, config)
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    # plateaus at ln(B) until logit_scale warms up, then collapses to ~0
    for _ in range(400):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.35, (losses[0], losses[-1])
    # after training, matching pairs dominate the similarity matrix
    img, txt, _ = clip_forward(params, images, tokens, lengths, config)
    sim = np.asarray(img @ txt.T)
    assert (sim.argmax(axis=1) == np.arange(4)).all()
