"""Core task/object API tests.

Modeled on the reference's python/ray/tests/test_basic*.py coverage: tasks,
object passing, nested tasks, multiple returns, errors, retries, wait,
cancellation, resource limits.
"""

import threading
import time

import pytest

import ray_tpu


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def fail(msg="boom"):
    raise ValueError(msg)


def test_put_get(runtime):
    ref = ray_tpu.put({"x": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"x": [1, 2, 3]}


def test_task_roundtrip(runtime):
    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_object_ref_args(runtime):
    a = ray_tpu.put(10)
    b = add.remote(a, 5)
    c = add.remote(b, ray_tpu.put(1))
    assert ray_tpu.get(c) == 16


def test_nested_tasks(runtime):
    @ray_tpu.remote
    def outer(n):
        refs = [add.remote(i, i) for i in range(n)]
        return sum(ray_tpu.get(refs))

    assert ray_tpu.get(outer.remote(5)) == 2 * sum(range(5))


def test_many_tasks(runtime):
    refs = [add.remote(i, 1) for i in range(200)]
    assert ray_tpu.get(refs) == [i + 1 for i in range(200)]


def test_num_returns(runtime):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3]) == [1, 2, 3]


def test_task_error_propagates(runtime):
    with pytest.raises(ray_tpu.TaskError) as ei:
        ray_tpu.get(fail.remote("kapow"))
    assert "kapow" in str(ei.value)
    assert isinstance(ei.value.cause, ValueError)


def test_error_propagates_through_dependency(runtime):
    bad = fail.remote()
    downstream = add.remote(bad, 1)
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(downstream)


def test_retries(runtime):
    counter = {"n": 0}
    lock = threading.Lock()

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        with lock:
            counter["n"] += 1
            if counter["n"] < 3:
                raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote()) == "ok"
    assert counter["n"] == 3


def test_wait(runtime):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast_ref = slow.remote(0.01)
    slow_ref = slow.remote(5.0)
    ready, not_ready = ray_tpu.wait([fast_ref, slow_ref], num_returns=1, timeout=2.0)
    assert ready == [fast_ref]
    assert not_ready == [slow_ref]


def test_get_timeout(runtime):
    @ray_tpu.remote
    def hang():
        time.sleep(60)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(hang.remote(), timeout=0.1)


def test_resources_limit_concurrency(runtime):
    # 8 CPUs, each task takes 4 => at most 2 run concurrently.
    running = []
    peak = []
    lock = threading.Lock()

    @ray_tpu.remote(num_cpus=4)
    def busy():
        with lock:
            running.append(1)
            peak.append(len(running))
        time.sleep(0.1)
        with lock:
            running.pop()
        return True

    refs = [busy.remote() for _ in range(6)]
    assert all(ray_tpu.get(refs))
    assert max(peak) <= 2


def test_infeasible_task_errors(runtime):
    @ray_tpu.remote(num_cpus=10_000)
    def impossible():
        return 1

    with pytest.raises(Exception):
        ray_tpu.get(impossible.remote(), timeout=5)


def test_cancel_pending(runtime):
    @ray_tpu.remote(num_cpus=8)
    def blocker():
        time.sleep(1.0)

    @ray_tpu.remote(num_cpus=8)
    def queued():
        return 1

    b = blocker.remote()
    q = queued.remote()
    assert ray_tpu.cancel(q)
    with pytest.raises((ray_tpu.TaskCancelledError, ray_tpu.TaskError)):
        ray_tpu.get(q, timeout=5)
    ray_tpu.get(b)


def test_custom_resources(runtime):
    runtime.scheduler.head_node().resources.add_capacity({"widget": 2.0})

    @ray_tpu.remote(resources={"widget": 1.0})
    def uses_widget():
        return "w"

    assert ray_tpu.get(uses_widget.remote()) == "w"
    assert ray_tpu.cluster_resources().get("widget") == 2.0


def test_cluster_resources(runtime):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 8.0


def test_cancel_blocked_task_no_deadlock(runtime):
    """Regression: cancel of a dependency-blocked task must not deadlock the
    scheduler (seal_error runs dependency callbacks inline)."""

    @ray_tpu.remote
    def slow():
        time.sleep(0.5)
        return 1

    upstream = slow.remote()
    downstream = add.remote(upstream, 1)
    chained = add.remote(downstream, 1)  # blocked on downstream
    ray_tpu.cancel(downstream)
    with pytest.raises((ray_tpu.TaskCancelledError, ray_tpu.TaskError)):
        ray_tpu.get(chained, timeout=5)
    # Scheduler must still be live:
    assert ray_tpu.get(add.remote(1, 1), timeout=5) == 2


def test_bad_bundle_index_fails_task_not_scheduler(runtime):
    """Regression: a dispatch-time error must fail the task, not kill the
    dispatch loop."""
    pg = ray_tpu.placement_group([{"CPU": 1}])
    strat = ray_tpu.PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=7)
    ref = add.options(scheduling_strategy=strat).remote(1, 2)
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(ref, timeout=5)
    assert ray_tpu.get(add.remote(1, 1), timeout=5) == 2
    ray_tpu.remove_placement_group(pg)


def test_wait_returns_at_most_num_returns(runtime):
    refs = [ray_tpu.put(i) for i in range(5)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=2, timeout=5)
    assert len(ready) == 2
    assert len(not_ready) == 3
    assert set(ready + not_ready) == set(refs)


def test_wait_and_get_scale_to_10k_refs(runtime):
    """The reference envelope is 10k+ refs in flight
    (release/benchmarks/README.md:29): wait() and list-get() over 10k
    already-sealed refs must complete in well under a second."""
    import time

    import ray_tpu

    refs = [ray_tpu.put(i) for i in range(10_000)]
    t0 = time.perf_counter()
    ready, rest = ray_tpu.wait(refs, num_returns=10_000, timeout=10)
    t_wait = time.perf_counter() - t0
    assert len(ready) == 10_000 and not rest
    assert t_wait < 1.0, f"wait over 10k refs took {t_wait:.2f}s"

    t0 = time.perf_counter()
    values = ray_tpu.get(refs, timeout=10)
    t_get = time.perf_counter() - t0
    assert values[9999] == 9999
    assert t_get < 1.0, f"get over 10k refs took {t_get:.2f}s"

    # partial wait keeps the contract at scale: at most num_returns ready
    ready, rest = ray_tpu.wait(refs, num_returns=7, timeout=10)
    assert len(ready) == 7 and len(rest) == 9_993
