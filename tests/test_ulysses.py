"""Ulysses (all-to-all head-scattered) context parallelism vs dense
reference — the second SP strategy next to ring attention (SURVEY §2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import mha_reference, ulysses_attention, ulysses_attention_sharded
from ray_tpu.parallel import MeshSpec, build_mesh


@pytest.fixture
def sp_mesh():
    return build_mesh(MeshSpec(sp=8))


@pytest.fixture
def sp4_mesh():
    return build_mesh(MeshSpec(dp=2, sp=4))


def _qkv(key, b, h, s, d, hkv=None):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, h, s, d)),
        jax.random.normal(kk, (b, hkv or h, s, d)),
        jax.random.normal(kv, (b, hkv or h, s, d)),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(sp_mesh, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 8, 128, 32)
    expected = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention_sharded(q, k, v, sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_ulysses_gqa(sp4_mesh):
    """GQA: kv heads repeat up to q heads before the head scatter."""
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 4, 64, 32, hkv=2)
    expected = mha_reference(q, k, v, causal=True)
    out = ulysses_attention_sharded(q, k, v, sp4_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5)


def test_ulysses_backward_matches_reference(sp4_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 4, 64, 16)

    def loss_u(q, k, v):
        out = ulysses_attention_sharded(q, k, v, sp4_mesh, causal=True)
        return jnp.sum(out * out)

    def loss_ref(q, k, v):
        out = mha_reference(q, k, v, causal=True)
        return jnp.sum(out * out)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ulysses_matches_ring(sp_mesh):
    """The two SP strategies are interchangeable on the same shards."""
    from ray_tpu.ops import ring_attention_sharded

    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 8, 128, 16)
    u = ulysses_attention_sharded(q, k, v, sp_mesh, causal=True)
    r = ring_attention_sharded(q, k, v, sp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r), atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    q, k, v = _qkv(jax.random.PRNGKey(4), 1, 4, 128, 16)  # 4 heads < sp=8
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh=sp_mesh, causal=False)


def test_ulysses_under_jit_keeps_sharding(sp_mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 8, 64, 16)
    spec = NamedSharding(sp_mesh, P(None, None, "sp", None))
    q, k, v = (jax.device_put(x, spec) for x in (q, k, v))
    fn = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh=sp_mesh, causal=True))
    out = fn(q, k, v)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v, causal=True)),
        atol=2e-5, rtol=2e-5,
    )
