"""Preemption-aware training + verified checkpoint restore.

The failure matrix rows this file covers (ISSUE 4): announced node loss
(PREEMPTING drain → emergency checkpoint → gang restart excluding the
node, failure budget untouched) and storage corruption (manifest-verified
restore with quarantine + fallback, torn-dir GC).
"""

import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.core import chaos
from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy


@pytest.fixture
def nodes4():
    """4 logical nodes x 1 CPU: a 3-worker gang spans 3 nodes, leaving
    one node free for the post-preemption restart."""
    rt = ray_tpu.init(num_cpus=1, num_nodes=4, detect_accelerators=False)
    yield rt
    chaos.clear_chaos()
    ray_tpu.shutdown()


@pytest.fixture
def nodes2():
    rt = ray_tpu.init(num_cpus=2, num_nodes=2, detect_accelerators=False)
    yield rt
    chaos.clear_chaos()
    ray_tpu.shutdown()


# ------------------------------------------------------------ chaos arming


def test_chaos_preempt_env_parsing(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_CHAOS",
        "preempt_node=1,preempt_warning_s=2.5,name_filter=trig,max_injections=1",
    )
    chaos.load_from_env()
    cfg = chaos._state.config
    assert cfg.preempt_node is True
    assert cfg.preempt_warning_s == 2.5
    assert cfg.name_filter == "trig"
    assert cfg.max_injections == 1
    chaos.clear_chaos()


def test_preempt_hook_fires_once_with_node(monkeypatch):
    """preempt_node consumes the injection budget and hands (node,
    warning, reason) to the registered hook instead of erroring/killing."""
    calls = []
    chaos.set_preemption_hook(lambda node, w, r: calls.append((node, w, r)))
    try:
        chaos.set_chaos(preempt_node=True, preempt_warning_s=1.5,
                        name_filter="victim", max_injections=1)
        chaos.maybe_inject("innocent", node="A")
        assert calls == []
        chaos.maybe_inject("victim-task", node="B")
        assert len(calls) == 1 and calls[0][0] == "B" and calls[0][1] == 1.5
        chaos.maybe_inject("victim-task", node="B")  # budget exhausted
        assert len(calls) == 1
    finally:
        chaos.clear_chaos()
        chaos.set_preemption_hook(None)


# ------------------------------------------------------- drain semantics


def test_drain_stops_new_placements(nodes2):
    """A PREEMPTING node takes no new tasks, actors, or PG bundles while
    it is still alive inside its warning window."""
    rt = nodes2
    victim = next(n for n in rt.scheduler.nodes() if not n.is_head)
    rt.preempt_node(victim, warning_s=60.0, reason="drill")
    assert victim.draining and victim.alive

    @ray_tpu.remote
    def where():
        return 1

    ray_tpu.get([where.remote() for _ in range(8)], timeout=30)
    placed = {e["node"] for e in rt.task_events() if e["name"] == "where"}
    assert victim.node_id.hex() not in placed

    # PG planning skips it: 2x{CPU:2} cannot fit on the one placeable node
    from ray_tpu.core.exceptions import PlacementGroupUnschedulableError

    with pytest.raises(PlacementGroupUnschedulableError):
        ray_tpu.placement_group([{"CPU": 2}, {"CPU": 2}], strategy="SPREAD")
    pg = ray_tpu.placement_group([{"CPU": 1}])  # fits the survivor
    assert pg.ready(timeout=10)
    assert pg.bundles[0].node is not victim

    # actors avoid it too
    @ray_tpu.remote
    class A:
        def ping(self):
            return "ok"

    a = A.options(num_cpus=1).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "ok"
    for ar in rt._actors.values():
        assert ar._node is not victim

    # observability: the state API shows the node PREEMPTING
    from ray_tpu.util import state

    states = {n["node_id"]: n["state"] for n in state.list_nodes()}
    assert states[victim.node_id.hex()] == "PREEMPTING"


def test_preempted_node_dies_after_window(nodes2):
    rt = nodes2
    victim = next(n for n in rt.scheduler.nodes() if not n.is_head)
    rt.preempt_node(victim, warning_s=0.2, reason="drill")
    deadline = time.monotonic() + 10
    while victim.alive and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not victim.alive
    assert victim not in rt.scheduler.nodes()


def test_sigterm_handler_begins_preemption():
    """health.install_preemption_signal_handler: SIGTERM = announced
    preemption, routed into ctx.begin_preemption with fate=shutdown."""
    import signal

    from ray_tpu.core.health import install_preemption_signal_handler

    calls = []

    class _Ctx:
        def begin_preemption(self, reason, warning_s=None, fate=None):
            calls.append((reason, fate))

    prev = install_preemption_signal_handler(_Ctx())
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not calls and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert calls and calls[0][1] == "shutdown"
    assert "SIGTERM" in calls[0][0]


# ------------------------------------------------------- capstone drill


def test_preempt_drill_capstone(nodes4, tmp_path):
    """A 3-worker gang under chaos preempt_node: emergency checkpoint
    inside the warning window, restart EXCLUDING the preempting node,
    resume from that checkpoint — with max_failures=0, so any budget
    consumption would fail the run."""
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )

    rt = nodes4
    starts = []        # first step of each attempt
    ckpt_steps = []    # every checkpoint step written
    emergency = []     # steps checkpointed BECAUSE of should_checkpoint()

    def train_fn(config):
        from ray_tpu import train

        ctx = train.get_context()
        state = train.get_checkpoint()
        start = int(state["step"]) + 1 if state is not None else 0
        if ctx.world_rank == 0:
            starts.append(start)
        for step in range(start, 60):
            time.sleep(0.02)  # one "train step"
            if ctx.world_rank != 0:
                if train.is_preempted():
                    return "preempted"  # yield: the node dies soon
                continue
            if train.should_checkpoint():
                # emergency checkpoint at the CURRENT step
                train.report({"step": step}, checkpoint={"step": step},
                             checkpoint_step=step)
                emergency.append(step)
                ckpt_steps.append(step)
            elif train.is_preempted():
                return "preempted"  # emergency checkpoint already taken
            elif step % 10 == 9:
                train.report({"step": step}, checkpoint={"step": step},
                             checkpoint_step=step)
                ckpt_steps.append(step)
            else:
                train.report({"step": step})
        return "done"

    controller = TrainController(
        train_fn,
        ScalingConfig(num_workers=3),
        RunConfig(name="preempt-drill", storage_path=str(tmp_path / "trial"),
                  failure=FailureConfig(max_failures=0)),
        train_config={},
        restart_backoff_s=0.0,
    )
    box = {}

    def run():
        box["result"] = controller.run()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    # wait for the gang to be running (first reports flowing)
    deadline = time.monotonic() + 60
    while not controller.metrics_history and time.monotonic() < deadline:
        time.sleep(0.02)
    assert controller.metrics_history, "gang never started reporting"

    # arm chaos and dispatch the trigger task onto a node hosting a gang
    # worker (its 1 CPU is held by the worker, so pick any full node)
    chaos.set_chaos(preempt_node=True, preempt_warning_s=3.0,
                    name_filter="preempt-trigger", max_injections=1)
    victim = next(
        n for n in rt.scheduler.nodes()
        if n.resources.available().get("CPU", 0.0) < 0.5
    )

    @ray_tpu.remote(name="preempt-trigger", num_cpus=0)
    def trigger():
        return "sent"

    ref = trigger.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(victim.node_id)
    ).remote()
    assert ray_tpu.get(ref, timeout=30) == "sent"

    thread.join(timeout=120)
    assert not thread.is_alive(), "controller never finished"
    result = box["result"]
    assert result.status == RunStatus.FINISHED, result.error
    # announced preemption: separate counter, failure budget untouched
    assert result.num_preempt_restarts == 1
    assert result.num_restarts == 0
    assert victim.draining
    # the emergency checkpoint landed inside the window...
    assert emergency, "no emergency checkpoint was taken"
    # ...and the restart resumed FROM it: within one checkpoint interval
    assert len(starts) == 2
    assert starts[1] == max(emergency) + 1
    assert result.checkpoint_step is not None


# --------------------------------------------- controller resume satellite


def test_resume_from_step_propagates_with_none_config(runtime):
    """controller.py satellite: train_config=None must not drop
    resume_from_step on restart — it defaults to {} and the train_fn
    receives the step."""
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )

    seen = []

    def train_fn(config=None):
        from ray_tpu import train

        seen.append(None if config is None else config.get("resume_from_step"))
        if len(seen) == 1:
            train.report({"loss": 1.0}, checkpoint_step=7)
            raise RuntimeError("first attempt dies")
        train.report({"loss": 0.1})
        return "ok"

    controller = TrainController(
        train_fn,
        ScalingConfig(num_workers=1),
        RunConfig(name="resume-none",
                  failure=FailureConfig(max_failures=1)),
        train_config=None,
        restart_backoff_s=0.0,
    )
    result = controller.run()
    assert result.status == RunStatus.FINISHED
    assert seen == [None, 7]


# -------------------------------------------------- checkpoint retention


def test_session_retention_configurable_and_protects_restore_step(tmp_path):
    from ray_tpu.train.session import Session, TrainContext, list_checkpoints

    session = Session(
        TrainContext(0, 1, "ret", trial_dir=str(tmp_path)),
        checkpoint_keep=4,
    )
    for step in range(6):
        session.save_checkpoint({"step": step}, step)
    assert len(list_checkpoints(str(tmp_path))) == 4

    prot_dir = tmp_path / "prot"
    session2 = Session(
        TrainContext(0, 1, "ret2", trial_dir=str(prot_dir)),
        checkpoint_keep=1,
    )
    session2.protect_step = 2  # a restore is pending on step 2
    for step in range(6):
        session2.save_checkpoint({"step": step}, step)
    left = list_checkpoints(str(prot_dir))
    assert left == ["ckpt_00000002.pkl", "ckpt_00000005.pkl"]


def test_session_retention_flag_default(tmp_path, monkeypatch):
    from ray_tpu.core.config import cfg
    from ray_tpu.train.session import Session, TrainContext, list_checkpoints

    monkeypatch.setenv("RAY_TPU_TRAIN_CKPT_KEEP", "3")
    assert cfg.train_ckpt_keep == 3
    session = Session(TrainContext(0, 1, "flag", trial_dir=str(tmp_path)))
    for step in range(5):
        session.save_checkpoint({"step": step}, step)
    assert len(list_checkpoints(str(tmp_path))) == 3


# ------------------------------------------------- verified restore (pkl)


def _fallback_count(store: str) -> float:
    from ray_tpu.util.metrics import registry

    metric = registry().get("raytpu_train_ckpt_fallback_total")
    if metric is None:
        return 0.0
    return sum(v for tags, v in metric.collect() if tags.get("store") == store)


def test_corrupt_session_checkpoint_falls_back(tmp_path):
    """Bit-rot in the newest pickle checkpoint: restore quarantines it
    and falls back to the previous VALID step instead of raising."""
    from ray_tpu.train.session import (
        Session, TrainContext, list_checkpoints, load_trial_checkpoint,
    )

    trial = str(tmp_path)
    session = Session(TrainContext(0, 1, "corrupt", trial_dir=trial),
                      checkpoint_keep=5)
    for step in (1, 2, 3):
        session.save_checkpoint({"step": step}, step)
    # flip bytes in the newest data file; its manifest now disagrees
    victim = os.path.join(trial, "ckpt_00000003.pkl")
    with open(victim, "r+b") as f:
        f.seek(0)
        f.write(b"\xde\xad\xbe\xef")
    before = _fallback_count("session")
    restored = load_trial_checkpoint(trial)
    assert restored == {"step": 2}  # newest VALID step
    assert _fallback_count("session") == before + 1
    # quarantined out of the naming scheme, so it is not retried
    assert "ckpt_00000003.pkl" not in list_checkpoints(trial)
    assert os.path.exists(victim + ".corrupt")
    # events carry the quarantine
    from ray_tpu.util.events import events

    msgs = [e["message"] for e in events().list(source="train", limit=50)]
    assert any("quarantined corrupt checkpoint" in m for m in msgs)


def test_torn_session_checkpoint_gc(tmp_path):
    from ray_tpu.train.session import Session, TrainContext, gc_torn_checkpoints

    trial = str(tmp_path)
    os.makedirs(trial, exist_ok=True)
    # a crash mid-save strands the staging file and an orphan manifest
    with open(os.path.join(trial, "ckpt_00000009.pkl.tmp"), "wb") as f:  # atomic-ok: test fixture simulating a torn write
        f.write(b"torn")
    with open(os.path.join(trial, "ckpt_00000008.pkl.manifest.json"), "w") as f:  # atomic-ok: test fixture
        f.write("{}")
    assert gc_torn_checkpoints(trial) == 2
    # save_checkpoint GCs implicitly too
    session = Session(TrainContext(0, 1, "gc", trial_dir=trial))
    with open(os.path.join(trial, "ckpt_00000010.pkl.tmp"), "wb") as f:  # atomic-ok: test fixture
        f.write(b"torn")
    session.save_checkpoint({"ok": True}, 11)
    assert not os.path.exists(os.path.join(trial, "ckpt_00000010.pkl.tmp"))


# ----------------------------------------------- verified restore (orbax)


def test_orbax_manifest_commit_fallback_and_gc(tmp_path):
    import numpy as np
    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import (
        COMMIT_NAME, CheckpointManager, MANIFEST_NAME,
    )

    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, max_to_keep=5)
    mgr.save(1, {"w": jnp.arange(8.0) * 1.0})
    mgr.save(2, {"w": jnp.arange(8.0) * 2.0})
    step_dir = os.path.join(d, "2")
    assert os.path.exists(os.path.join(step_dir, COMMIT_NAME))
    with open(os.path.join(step_dir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    assert manifest["files"], "manifest recorded no files"
    # corrupt one manifested payload file of step 2
    rel = sorted(manifest["files"])[0]
    with open(os.path.join(step_dir, rel), "ab") as f:
        f.write(b"bitrot")
    before = _fallback_count("orbax")
    restored = mgr.restore({"w": jnp.zeros(8)})
    np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(8.0))
    assert _fallback_count("orbax") == before + 1
    assert mgr.latest_step() == 1  # quarantined step left the step view
    assert any(
        name.startswith("2.corrupt") for name in os.listdir(d)
    ), os.listdir(d)
    mgr.close()

    # torn-dir GC at init: an uncommitted integer step dir disappears
    torn = os.path.join(d, "7")
    os.makedirs(torn)
    with open(os.path.join(torn, "junk"), "wb") as f:  # atomic-ok: test fixture simulating a torn save
        f.write(b"partial")
    mgr2 = CheckpointManager(d, max_to_keep=5)
    assert not os.path.exists(torn)
    assert mgr2.all_steps() == [1]
    restored2 = mgr2.restore({"w": jnp.zeros(8)})
    np.testing.assert_allclose(np.asarray(restored2["w"]), np.arange(8.0))
    mgr2.close()


# ------------------------------------------------------- pubsub satellite


def test_pubsub_subscriber_failure_warns_once():
    from ray_tpu.core.gcs import GlobalControlStore
    from ray_tpu.util.events import events

    gcs = GlobalControlStore()

    def bad(_msg):
        raise RuntimeError("dead listener")

    gcs.pubsub.subscribe("preempt-test-chan", bad)
    gcs.pubsub.publish("preempt-test-chan", {"n": 1})
    gcs.pubsub.publish("preempt-test-chan", {"n": 2})
    warnings = [
        e for e in events().list(source="gcs", limit=200)
        if "preempt-test-chan" in e["message"]
    ]
    assert len(warnings) == 1, warnings
    # a healthy subscriber still receives everything
    got = []
    gcs.pubsub.subscribe("preempt-test-chan", got.append)
    gcs.pubsub.publish("preempt-test-chan", {"n": 3})
    assert got == [{"n": 3}]


# ------------------------------------------------------------ static check


def test_atomic_writes_static_check():
    """scripts/check_atomic_writes.py is now a shim over the raylint
    atomic-writes rule; the repo-wide gate runs ONCE in
    tests/test_raylint.py. Here: the shim still flags a tree whose
    state writes skip tmp + os.replace."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "check_atomic_writes.py"
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location("caw", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with tempfile.TemporaryDirectory() as tmp:
        bad_root = pathlib.Path(tmp)
        (bad_root / "train").mkdir(parents=True)
        (bad_root / "core").mkdir()
        (bad_root / "core" / "gcs.py").write_text(
            'def snap(path, blob):\n'
            '    with open(path, "wb") as f:\n'
            '        f.write(blob)\n'
        )
        (bad_root / "train" / "ckpt.py").write_text(
            'import json\n'
            'def save(path, obj):\n'
            '    with open(path, "w") as f:\n'
            '        json.dump(obj, f)\n'
        )
        assert mod.main(["caw", str(bad_root)]) == 1
