"""RPC control plane + chunked object transfer + GCS-as-a-service
(reference: src/ray/rpc/, object_manager/ Push/Pull, gcs_server/client)."""

import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from ray_tpu.core.gcs import GlobalControlStore
from ray_tpu.core.gcs_service import GcsClient, serve_gcs
from ray_tpu.core.ids import JobID, ObjectID
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.core.object_transfer import (
    CHUNK_BYTES,
    ObjectTransferServer,
    fetch_object,
    push_object,
)
from ray_tpu.core.rpc import RpcClient, RpcError, RpcServer


# --------------------------------------------------------------------- rpc


def test_rpc_roundtrip_and_errors():
    server = RpcServer({
        "add": lambda a, b: a + b,
        "fail": lambda: (_ for _ in ()).throw(ValueError("remote boom")),
        "echo_kw": lambda **kw: kw,
    })
    try:
        client = RpcClient(server.url)
        assert client.call("add", 2, 3) == 5
        assert client.add(10, b=20) == 30  # attr sugar
        assert client.call("echo_kw", x=1) == {"x": 1}
        with pytest.raises(ValueError, match="remote boom"):
            client.call("fail")
        with pytest.raises(AttributeError, match="no rpc method"):
            client.call("nope")
        client.close()
    finally:
        server.stop()


def test_rpc_oserror_from_handler_is_not_retried():
    """A handler exception that subclasses OSError (FileNotFoundError,
    TimeoutError...) must re-raise typed on the client WITHOUT being
    mistaken for a transport failure — no connection teardown, no
    re-execution of the (possibly non-idempotent) handler."""
    calls = {"n": 0}

    def missing():
        calls["n"] += 1
        raise FileNotFoundError("no such working_dir")

    server = RpcServer({"missing": missing, "ok": lambda: 1})
    try:
        client = RpcClient(server.url, retries=3, retry_wait_s=0.01)
        with pytest.raises(FileNotFoundError, match="no such working_dir"):
            client.call("missing")
        assert calls["n"] == 1, "handler was re-executed by transport retry"
        # the connection survived: next call reuses it
        assert client.call("ok") == 1
        assert client._sock is not None
        client.close()
    finally:
        server.stop()


def test_rpc_reconnects_after_server_restart():
    server = RpcServer({"val": lambda: 1}, port=0)
    port = server.address[1]
    client = RpcClient(f"127.0.0.1:{port}", retries=5, retry_wait_s=0.2)
    assert client.call("val") == 1
    server.stop()

    def restart():
        import time

        time.sleep(0.4)
        restart.server = RpcServer({"val": lambda: 2}, port=port)

    t = threading.Thread(target=restart)
    t.start()
    try:
        assert client.call("val") == 2  # retried across the outage
    finally:
        t.join()
        restart.server.stop()
        client.close()


def test_rpc_dead_server_raises_rpc_error():
    client = RpcClient("127.0.0.1:1", timeout=0.5, retries=0)
    with pytest.raises(RpcError):
        client.call("anything")


def test_rpc_concurrent_clients():
    server = RpcServer({"square": lambda x: x * x})
    try:
        results = {}

        def worker(i):
            c = RpcClient(server.url)
            results[i] = [c.square(j) for j in range(20)]
            c.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results[i] == [j * j for j in range(20)] for i in range(8))
    finally:
        server.stop()


# ----------------------------------------------------------- object transfer


def test_pull_and_push_objects_chunked():
    store = ObjectStore()
    server = ObjectTransferServer(store)
    try:
        # multi-chunk payload: > 2 chunks of 4 MiB
        big = np.arange(3 * CHUNK_BYTES // 8, dtype=np.float64)
        oid = ObjectID.for_put(JobID.next())
        store.put(oid, big)
        fetched = fetch_object(server.address, oid.hex())
        np.testing.assert_array_equal(fetched, big)

        # push the other way: lands sealed in the remote store
        oid2 = ObjectID.for_put(JobID.next())
        push_object(server.address, oid2.hex(), {"nested": [1, 2, 3]})
        assert store.get(oid2, timeout=5) == {"nested": [1, 2, 3]}
    finally:
        server.stop()


def test_abandoned_transfer_swept_by_ttl(monkeypatch):
    """A client that begins a pull and dies must not pin the payload in
    the serving process forever: stale transfers are TTL-swept."""
    import ray_tpu.core.object_transfer as ot

    store = ObjectStore()
    server = ObjectTransferServer(store)
    try:
        oid = ObjectID.for_put(JobID.next())
        store.put(oid, np.arange(1000))
        client = RpcClient(server.address)
        info = client.call("pull_begin", oid.hex())  # ...then "die"
        assert info["transfer_id"] in server._outgoing
        monkeypatch.setattr(ot, "TRANSFER_TTL_S", 0.0)
        # any later begin sweeps stale entries
        client.call("pull_begin", oid.hex())
        assert info["transfer_id"] not in server._outgoing
        client.close()
    finally:
        server.stop()


def test_transfer_streams_without_monolithic_copy():
    """Out-of-band pickle-5 transfer: a numpy payload's buffer is served
    as windows of the ORIGINAL array memory — the sender never builds a
    monolithic payload-sized pickle blob (peak ~1x object size)."""
    store = ObjectStore()
    server = ObjectTransferServer(store)
    try:
        big = np.arange(3 * CHUNK_BYTES // 8, dtype=np.float64)
        oid = ObjectID.for_put(JobID.next())
        store.put(oid, big)
        client = RpcClient(server.address)
        info = client.call("pull_begin", oid.hex())
        # the out-of-band buffer IS the array's memory, not a copy
        tr = server._outgoing[info["transfer_id"]]
        assert any(
            mv.obj is big or np.shares_memory(np.frombuffer(mv, np.float64), big)
            for mv in tr.buffers
            if len(mv) == big.nbytes
        )
        client.call("pull_end", info["transfer_id"])
        client.close()
    finally:
        server.stop()


def test_cross_process_object_pull():
    """The real story: a SEPARATE OS process serves its store; we pull."""
    code = textwrap.dedent("""
        import sys
        import numpy as np
        from ray_tpu.core.ids import JobID, ObjectID
        from ray_tpu.core.object_store import ObjectStore
        from ray_tpu.core.object_transfer import ObjectTransferServer

        store = ObjectStore()
        oid = ObjectID.for_put(JobID.next())
        store.put(oid, np.arange(100000))
        server = ObjectTransferServer(store)
        print(server.address, oid.hex(), flush=True)
        sys.stdin.readline()  # hold until the parent is done
    """)
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stdin=subprocess.PIPE, text=True,
    )
    try:
        address, oid_hex = proc.stdout.readline().split()
        value = fetch_object(address, oid_hex)
        np.testing.assert_array_equal(value, np.arange(100000))
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


# --------------------------------------------------------------- gcs service


def test_gcs_service_cross_process():
    """Head process serves its GCS; a worker process coordinates through
    it (KV + pubsub + named-actor existence)."""
    gcs = GlobalControlStore()
    gcs.kv.put("world_size", 4, namespace="train")
    gcs.register_named_actor("coordinator", object())
    server = serve_gcs(gcs)
    try:
        code = textwrap.dedent(f"""
            from ray_tpu.core.gcs_service import GcsClient

            c = GcsClient("{server.url}")
            assert c.ping()
            assert c.kv_get("world_size", namespace="train") == 4
            c.kv_put("rank0_ready", True, namespace="train")
            assert c.has_named_actor("coordinator")
            assert not c.has_named_actor("nobody")
            c.publish("events", {{"hello": "from-worker"}})
            print("WORKER-OK")
        """)
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60,
        )
        assert "WORKER-OK" in out.stdout, out.stderr
        # worker's writes are visible in the head's store
        assert gcs.kv.get("rank0_ready", namespace="train") is True
        msgs = gcs.pubsub.poll("events")
        assert any(m[1] == {"hello": "from-worker"} for m in msgs)
    finally:
        server.stop()


def test_gcs_client_poll_subscription():
    gcs = GlobalControlStore()
    server = serve_gcs(gcs)
    try:
        client = GcsClient(server.url)
        gcs.pubsub.publish("ch", "m1")
        gcs.pubsub.publish("ch", "m2")
        msgs = [m for _, m in client.poll("ch")]
        assert msgs == ["m1", "m2"]
        client.close()
    finally:
        server.stop()


def test_resource_sync_and_staleness():
    gcs = GlobalControlStore()
    server = serve_gcs(gcs)
    try:
        c = GcsClient(server.url)
        c.report_resources("node-a", {"CPU": 8, "TPU": 4})
        c.report_resources("node-b", {"CPU": 8})
        view = c.cluster_view()
        assert view["total"] == {"CPU": 16.0, "TPU": 4.0}
        assert set(view["nodes"]) == {"node-a", "node-b"}
        # a stale node ages out of the aggregate (liveness by silence)
        server.syncer._views["node-b"] = (0.0, {"CPU": 8})
        view = c.cluster_view()
        assert view["total"] == {"CPU": 8.0, "TPU": 4.0}
        assert set(view["nodes"]) == {"node-a"}
        c.close()
    finally:
        server.stop()


def test_function_export_cross_process():
    """Driver exports a function by value; a separate process fetches and
    runs it (reference function_manager via GCS KV)."""
    gcs = GlobalControlStore()
    server = serve_gcs(gcs)
    try:
        client = GcsClient(server.url)
        factor = 7

        def scale(x):
            return x * factor  # closure travels by value

        client.register_function("scale", scale)
        code = textwrap.dedent(f"""
            from ray_tpu.core.gcs_service import GcsClient

            c = GcsClient("{server.url}")
            fn = c.fetch_function("scale")
            assert fn(6) == 42, fn(6)
            assert c.fetch_function("missing") is None
            print("FUNC-OK")
        """)
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=60,
        )
        assert "FUNC-OK" in out.stdout, out.stderr
        client.close()
    finally:
        server.stop()
