"""Native arena allocator: alloc/free/coalesce, pins, LRU eviction, zero-copy."""

import numpy as np
import pytest

from ray_tpu.core.native_store import NativeArena, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native objstore not built (no g++?)"
)


@pytest.fixture
def arena():
    a = NativeArena(1 << 20)  # 1 MiB
    yield a
    a.close()


def test_put_get_roundtrip(arena):
    data = b"hello world" * 100
    assert arena.put(1, data)
    view = arena.get(1)
    assert bytes(view) == data
    arena.unpin(1)
    assert arena.num_objects == 1


def test_numpy_zero_copy(arena):
    x = np.arange(1000, dtype=np.float32)
    assert arena.put(2, x.tobytes())
    view = arena.get(2)
    y = np.frombuffer(view, dtype=np.float32)
    np.testing.assert_array_equal(x, y)
    arena.unpin(2)


def test_delete_frees_and_coalesces(arena):
    for i in range(8):
        assert arena.put(i, bytes(1000))
    used_before = arena.used
    # delete adjacent objects: free blocks must coalesce
    for i in range(8):
        assert arena.delete(i)
    assert arena.used == 0
    assert arena.num_free_blocks == 1  # fully coalesced back to one block
    assert used_before > 0


def test_full_arena_rejects(arena):
    big = bytes((1 << 20) - 64)
    assert arena.put(1, big)
    assert not arena.put(2, bytes(1024))


def test_pinned_objects_not_evictable(arena):
    assert arena.put(1, bytes(512 << 10))
    view = arena.get(1)  # pinned
    assert arena.lru_candidate() is None  # nothing evictable
    assert not arena.delete(1)  # pinned objects cannot be deleted
    arena.unpin(1)
    assert arena.lru_candidate() == 1
    assert arena.delete(1)
    _ = view  # keep the view alive through the pin window


def test_lru_order_and_eviction_loop(arena):
    third = 300 << 10  # 3 × 300KiB fills the 1MiB arena
    for i in (1, 2, 3):
        assert arena.put(i, bytes(third))
    # touch 1 so 2 becomes oldest
    arena.unpin(1) if False else None
    v = arena.get(1)
    arena.unpin(1)
    assert arena.lru_candidate() == 2

    evicted = []
    ok = arena.put_with_eviction(4, bytes(third), on_evict=lambda i, _: evicted.append(i))
    assert ok
    assert evicted and evicted[0] == 2
    assert arena.get(2) is None
    _ = v


def test_duplicate_id_rejected(arena):
    assert arena.put(7, b"x")
    assert arena.put(7, b"y") is False


def test_many_small_objects_fragmentation(arena):
    # interleaved alloc/free exercises the free-list
    for round_ in range(5):
        ids = list(range(round_ * 100, round_ * 100 + 100))
        for i in ids:
            assert arena.put(i, bytes(np.random.default_rng(i).integers(100, 2000)))
        for i in ids[::2]:
            assert arena.delete(i)
        for i in ids[1::2]:
            view = arena.get(i)
            assert view is not None
            arena.unpin(i)
            assert arena.delete(i)
    assert arena.num_objects == 0
    assert arena.used == 0
    assert arena.num_free_blocks == 1


# ----------------------------- ObjectStore integration (RAY_TPU_NATIVE_STORE)


def test_object_store_shm_tier_roundtrip(monkeypatch, tmp_path):
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "1")
    from ray_tpu.core.ids import JobID, ObjectID, TaskID
    from ray_tpu.core.object_store import ObjectStore, Tier

    store = ObjectStore(capacity_bytes=4 << 20, spill_dir=str(tmp_path))
    assert store._arena is not None
    task = TaskID.of(JobID.next())
    oid = ObjectID.for_task_return(task, 0)
    arr = np.arange(100_000, dtype=np.float32)  # 400KB > SHM threshold
    store.put(oid, arr)
    assert store.entry(oid).tier == Tier.SHM
    out = store.get(oid)
    np.testing.assert_array_equal(out, arr)
    assert store.stats["shm_puts"] == 1
    store.free(oid)
    assert store._arena.num_objects == 0


def test_object_store_shm_eviction_spills_to_disk(monkeypatch, tmp_path):
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "1")
    from ray_tpu.core.ids import JobID, ObjectID, TaskID
    from ray_tpu.core.object_store import ObjectStore, Tier

    # arena fits ~2 of the 400KB arrays (1MB capacity)
    store = ObjectStore(capacity_bytes=1 << 20, spill_dir=str(tmp_path))
    task = TaskID.of(JobID.next())
    oids, arrays = [], []
    for i in range(4):
        oid = ObjectID.for_task_return(task, i)
        arr = np.full(100_000, i, dtype=np.float32)
        store.put(oid, arr)
        oids.append(oid)
        arrays.append(arr)
    assert store.stats["shm_evictions"] >= 2
    # every object still readable: SHM or restored from spill
    for oid, arr in zip(oids, arrays):
        np.testing.assert_array_equal(store.get(oid), arr)


def test_small_and_object_dtype_bypass_shm(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "1")
    from ray_tpu.core.ids import JobID, ObjectID, TaskID
    from ray_tpu.core.object_store import ObjectStore, Tier

    store = ObjectStore(capacity_bytes=1 << 20)
    task = TaskID.of(JobID.next())
    o1 = ObjectID.for_task_return(task, 0)
    store.put(o1, np.arange(10))  # tiny -> inline
    assert store.entry(o1).tier == Tier.INLINE
    o2 = ObjectID.for_task_return(task, 1)
    store.put(o2, "not an array")
    assert store.entry(o2).tier == Tier.INLINE


def test_shared_arena_cross_process_descriptor(tmp_path):
    """A second OS process mmaps the arena file and reads a sealed
    payload ZERO-COPY via its (offset, size) descriptor (the plasma
    client protocol, plasma/store.h:55)."""
    import subprocess
    import sys

    from ray_tpu.core.native_store import NativeArena, ShmView, native_available

    if not native_available():
        pytest.skip("native store unavailable")
    path = str(tmp_path / "arena")
    arena = NativeArena(1 << 20, path=path)
    arr = np.arange(5000, dtype=np.float64)
    assert arena.put(42, arr.tobytes())
    desc = arena.descriptor(42)
    assert desc is not None
    _, offset, size = desc
    view = ShmView(path, offset, size // 8, "float64", (5000,))

    import pickle

    script = (
        "import pickle,sys,numpy as np\n"
        "v = pickle.load(sys.stdin.buffer)\n"
        "assert not v.flags.writeable  # plasma semantics: immutable\n"
        "print(float(v.sum()), float(v[4321]))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", script], input=pickle.dumps(view),
        capture_output=True, timeout=60, env={**__import__('os').environ,
                                              "PYTHONPATH": "."},
    )
    assert out.returncode == 0, out.stderr.decode()
    total, probe = out.stdout.decode().split()
    assert float(total) == float(arr.sum())
    assert float(probe) == 4321.0
    arena.release_descriptor(42)
    arena.close()


def test_process_task_gets_zero_copy_shm_arg(monkeypatch):
    """End to end: a big SHM-tier array passed to a process-executor
    task arrives as a read-only zero-copy view (no pipe pickling of the
    payload)."""
    import ray_tpu
    from ray_tpu.core.native_store import native_available

    if not native_available():
        pytest.skip("native store unavailable")
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "1")
    monkeypatch.setenv("RAY_TPU_SHM_MIN_BYTES", "1024")
    ray_tpu.init(num_cpus=2, detect_accelerators=False)
    try:
        big = ray_tpu.put(np.arange(200_000, dtype=np.float64))  # 1.6 MB

        @ray_tpu.remote(executor="process")
        def probe(arr):
            import numpy as _np

            # zero-copy plasma semantics: the arg is a read-only VIEW
            # (its base buffer is the mmap), not a pipe-copied array
            assert not arr.flags.writeable
            assert arr.base is not None
            return float(_np.sum(arr)), arr.shape

        total, shape = ray_tpu.get(probe.remote(big), timeout=120)
        assert total == float(np.arange(200_000, dtype=np.float64).sum())
        assert tuple(shape) == (200_000,)
        # the arena pin was released after the task
        store = ray_tpu.core.runtime.get_runtime().object_store
        entry = store.entry(big.object_id)
        from ray_tpu.core.object_store import Tier

        assert entry.tier == Tier.SHM
    finally:
        ray_tpu.shutdown()
