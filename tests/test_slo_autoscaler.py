"""SLO-driven replica autoscaling drill.

The scenario the burn term exists for: latency is burning (TTFT p99 over
objective) while the ongoing-request count still looks fine — queued work
waiting on slow TTFT registers as few ongoing requests, so the reference
heuristic never scales. The drill injects a burn window into the
ServeSLOMonitor ledger under real-but-light demand, watches the
controller scale UP one replica with reason "slo_burn", then go idle and
scale back DOWN through the graceful drain path — the whole episode
reconstructable afterward from the event log and the
raytpu_serve_slo_attainment gauge alone.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import cfg
from ray_tpu.util.events import events
from ray_tpu.util.metrics import get_or_create_histogram, registry
from ray_tpu.util.watchdog import serve_slo_monitor

# boundaries must match the span-derived histogram (tracing.py) so the
# drill hits the registered series instead of shadowing it
_TTFT_BOUNDS = (0.005, 0.025, 0.1, 0.5, 2.0, 10.0)


@pytest.fixture
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    cfg.set(
        serve_slo_ttft_p99_s=0.1,
        autoscale_burn_windows=1,
        autoscale_pressure_floor=0.25,
    )
    yield runtime
    serve.shutdown()
    ray_tpu.shutdown()
    cfg.reset()


def _burn_one_window(n: int = 32) -> None:
    """Feed the TTFT histogram n samples far over the 0.1s objective and
    run one monitor evaluation: exactly one new violated window."""
    hist = get_or_create_histogram(
        "raytpu_serve_ttft_seconds",
        "Time to first generated token, from engine request spans.",
        boundaries=_TTFT_BOUNDS,
    )
    for _ in range(n):
        hist.observe(5.0)
    report = serve_slo_monitor().check()
    assert report.get("ttft_p99", 0.0) > 0.1


def _wait(predicate, timeout=20.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    pytest.fail(msg or "condition not reached in time")


def test_slo_burn_scales_up_then_idle_drains_down(rt):
    release = threading.Event()

    @serve.deployment
    class Sticky:
        def __call__(self, x):
            release.wait(timeout=60)
            return x

    auto = serve.AutoscalingConfig(
        min_replicas=1, max_replicas=3,
        # generous ongoing target: 2 in-flight requests = desired 0.5
        # replicas, so the BASE heuristic never asks for a second one —
        # only the SLO term can (and 0.5 demand clears the 0.25 floor)
        target_ongoing_requests=4.0,
        scale_down_delay_s=1.0,
        slo_driven=True,
    )
    t0 = time.time()
    handle = serve.run(
        Sticky.options(name="sticky", autoscaling=auto).bind()
    )
    # prime: one monitor pass + one autoscale pass absorb any violation
    # history from earlier tests into the per-deployment high-water mark
    serve_slo_monitor().check()
    time.sleep(0.6)
    assert serve.status()["sticky"]["target_replicas"] == 1

    refs = [handle.remote(i) for i in range(2)]  # light, real demand
    _wait(lambda: serve.status()["sticky"]["ongoing"] >= 2,
          msg=f"demand never registered: {serve.status()}")

    _burn_one_window()
    _wait(lambda: serve.status()["sticky"]["target_replicas"] >= 2,
          msg=f"burn never scaled up: {serve.status()}")
    _wait(lambda: serve.status()["sticky"]["live_replicas"] >= 2,
          msg=f"second replica never started: {serve.status()}")

    # drain the demand: idle deployment must come back down -- and must
    # do it through the DRAINING path, not a kill
    release.set()
    ray_tpu.get(refs, timeout=60)
    _wait(lambda: serve.status()["sticky"]["target_replicas"] == 1,
          timeout=30, msg=f"never scaled back down: {serve.status()}")
    _wait(lambda: serve.status()["sticky"]["live_replicas"] == 1
          and serve.status()["sticky"]["draining_replicas"] == 0,
          timeout=30, msg=f"drain never completed: {serve.status()}")

    # ---- postmortem: the episode must be reconstructable from the event
    # log + the attainment gauge, with no access to the live controller
    log = events().list(kind="serve.autoscale", since_ts=t0, limit=100)
    ups = [e for e in log if e["extra"]["direction"] == "up"]
    downs = [e for e in log if e["extra"]["direction"] == "down"]
    assert ups and ups[0]["extra"]["reason"] == "slo_burn", log
    assert ups[0]["extra"]["burn_windows"] >= 1
    assert ups[0]["extra"]["target_replicas"] == 2
    assert downs and downs[-1]["extra"]["target_replicas"] == 1
    scaled = events().list(kind="serve.scaled", since_ts=t0, limit=100)
    assert any(e["extra"]["direction"] == "up" for e in scaled)
    assert any(e["extra"]["direction"] == "down" for e in scaled)
    drains = events().list(kind="serve.drain", since_ts=t0, limit=100)
    assert drains, "scale-down bypassed the graceful drain path"
    gauge = registry().get("raytpu_serve_slo_attainment")
    assert gauge is not None
    attained = {t.get("slo"): v for t, v in gauge.collect()}
    assert attained.get("ttft_p99", 1.0) < 1.0  # the burn left a record


def test_burn_resets_scale_down_damper(rt):
    """A burning deployment must never shed capacity: burn windows during
    the scale-down delay push the damper forward instead of letting the
    idle target drop."""

    @serve.deployment
    class Quick:
        def __call__(self, x):
            return x

    auto = serve.AutoscalingConfig(
        min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
        scale_down_delay_s=0.4, slo_driven=True,
    )
    serve.run(Quick.options(name="quick", autoscaling=auto).bind())
    serve_slo_monitor().check()
    time.sleep(0.6)

    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["quick"]
    state.target_replicas = 2  # as if a previous burn scaled us up
    # keep burning while idle: the damper must keep resetting
    for _ in range(4):
        _burn_one_window()
        time.sleep(0.3)
        assert serve.status()["quick"]["target_replicas"] == 2, (
            "burning deployment shed capacity"
        )
    # burn stops: the idle scale-down finally lands after the delay
    _wait(lambda: serve.status()["quick"]["target_replicas"] == 1,
          timeout=30, msg=f"idle scale-down never landed: {serve.status()}")


def test_pressure_floor_gates_burn_scale_up(rt):
    """An SLO burn with NO demand behind it (idle deployment, empty
    batches) must not scale up — cold-start artifacts and stray burns
    don't buy replicas."""

    @serve.deployment
    class Idle:
        def __call__(self, x):
            return x

    auto = serve.AutoscalingConfig(
        min_replicas=1, max_replicas=3, target_ongoing_requests=4.0,
        scale_down_delay_s=1.0, slo_driven=True,
    )
    serve.run(Idle.options(name="idle", autoscaling=auto).bind())
    serve_slo_monitor().check()
    time.sleep(0.6)

    _burn_one_window()
    time.sleep(1.0)  # several reconcile passes
    assert serve.status()["idle"]["target_replicas"] == 1, serve.status()
