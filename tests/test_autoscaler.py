"""Autoscaler: demand-driven scale up/down (reference:
autoscaler/_private/autoscaler.py:172), with both logical nodes and
REAL worker-agent processes (LocalProcessNodeProvider)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.autoscaler import (
    Autoscaler,
    FakeNodeProvider,
    LocalProcessNodeProvider,
    NodeType,
)


def test_fake_provider_scales_up_and_down():
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=0.5,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=4)
        def big():
            return "ran"

        # needs 4 CPUs; only a scaled-up node can satisfy it
        assert ray_tpu.get(big.remote(), timeout=60) == "ran"
        assert scaler.stats["scale_ups"] >= 1
        # idle node reaped after the timeout
        deadline = time.monotonic() + 30
        while scaler.stats["scale_downs"] == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert scaler.stats["scale_downs"] >= 1
        scaler.stop()
    finally:
        ray_tpu.shutdown()


def test_local_process_provider_spawns_real_agents():
    """Scale-up launches an actual `ray_tpu start` OS process that joins
    the cluster; the demanded task executes THERE; scale-down shuts the
    agent down again."""
    import os

    rt = ray_tpu.init(
        num_cpus=1, detect_accelerators=False, head=True,
        _system_config={"node_heartbeat_s": 0.2, "node_stale_s": 2.5},
    )
    provider = None
    try:
        provider = LocalProcessNodeProvider(rt)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("worker4", {"CPU": 4.0})],
            poll_interval_s=0.1, idle_timeout_s=1.0,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=4)
        def whereami():
            import os as _os

            return _os.getpid()

        pid = ray_tpu.get(whereami.remote(), timeout=120)
        assert pid != os.getpid(), "task should run on the autoscaled agent"
        # the task can finish before create_node's join-poll returns and
        # the scaler increments its counter — poll briefly
        deadline = time.monotonic() + 30
        while scaler.stats["scale_ups"] == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert scaler.stats["scale_ups"] == 1
        # the agent process is reaped once idle
        deadline = time.monotonic() + 60
        while scaler.stats["scale_downs"] == 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert scaler.stats["scale_downs"] == 1
        scaler.stop()
    finally:
        if provider is not None:
            provider.shutdown()
        ray_tpu.shutdown()
        from ray_tpu.core.config import cfg

        cfg.reset()


def test_unprovisionable_demand_fails_loudly():
    """With a scaler attached, demand NO node type can ever cover must
    raise OutOfResourcesError instead of queueing silently forever."""
    from ray_tpu.core.exceptions import OutOfResourcesError

    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=5.0,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=64)
        def impossible():
            return "never"

        with pytest.raises(OutOfResourcesError):
            ray_tpu.get(impossible.remote(), timeout=30)
        scaler.stop()
    finally:
        ray_tpu.shutdown()
