"""Autoscaler: demand-driven scale up/down (reference:
autoscaler/_private/autoscaler.py:172), with both logical nodes and
REAL worker-agent processes (LocalProcessNodeProvider)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.autoscaler import (
    Autoscaler,
    FakeNodeProvider,
    LocalProcessNodeProvider,
    NodeType,
)


def test_fake_provider_scales_up_and_down():
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=0.5,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=4)
        def big():
            return "ran"

        # needs 4 CPUs; only a scaled-up node can satisfy it
        assert ray_tpu.get(big.remote(), timeout=60) == "ran"
        assert scaler.stats["scale_ups"] >= 1
        # idle node reaped after the timeout
        deadline = time.monotonic() + 30
        while scaler.stats["scale_downs"] == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert scaler.stats["scale_downs"] >= 1
        scaler.stop()
    finally:
        ray_tpu.shutdown()


def test_local_process_provider_spawns_real_agents():
    """Scale-up launches an actual `ray_tpu start` OS process that joins
    the cluster; the demanded task executes THERE; scale-down shuts the
    agent down again."""
    import os

    rt = ray_tpu.init(
        num_cpus=1, detect_accelerators=False, head=True,
        _system_config={"node_heartbeat_s": 0.2, "node_stale_s": 2.5},
    )
    provider = None
    try:
        provider = LocalProcessNodeProvider(rt)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("worker4", {"CPU": 4.0})],
            poll_interval_s=0.1, idle_timeout_s=1.0,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=4)
        def whereami():
            import os as _os

            return _os.getpid()

        pid = ray_tpu.get(whereami.remote(), timeout=120)
        assert pid != os.getpid(), "task should run on the autoscaled agent"
        # the task can finish before create_node's join-poll returns and
        # the scaler increments its counter — poll briefly
        deadline = time.monotonic() + 30
        while scaler.stats["scale_ups"] == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert scaler.stats["scale_ups"] == 1
        # the agent process is reaped once idle
        deadline = time.monotonic() + 60
        while scaler.stats["scale_downs"] == 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert scaler.stats["scale_downs"] == 1
        scaler.stop()
    finally:
        if provider is not None:
            provider.shutdown()
        ray_tpu.shutdown()
        from ray_tpu.core.config import cfg

        cfg.reset()


def test_scale_down_skips_actor_hosting_and_preempting_nodes():
    """Lifecycle discipline: a node hosting a live (even zero-resource)
    actor is pinned, and a PREEMPTING node belongs to the preemption
    path — neither is ever selected for scale-down."""
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=0.2, drain_grace_s=0.5,
            runtime=rt,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=4)
        def big():
            return "ran"

        assert ray_tpu.get(big.remote(), timeout=60) == "ran"
        node = provider.created[0]

        # zero-resource actor on the scaled node: the node LOOKS idle
        # (resources fully free) but hosts live state — only the pin
        # check keeps it alive
        @ray_tpu.remote(num_cpus=0)
        class Pin:
            def ping(self):
                return "pong"

        pin = Pin.options(
            scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
                node.node_id
            )
        ).remote()
        assert ray_tpu.get(pin.ping.remote(), timeout=30) == "pong"
        time.sleep(1.0)  # several idle timeouts
        assert scaler.stats["scale_downs"] == 0
        assert node.alive and not node.draining

        # now simulate an announced preemption: still never selected
        # (and never terminated) by the scaler — the preemption path
        # owns the node's fate
        ray_tpu.kill(pin)
        rt.scheduler.mark_node_draining(
            node.node_id.hex(), "test preemption",
            deadline=time.time() + 60,
        )
        time.sleep(1.0)
        assert scaler.stats["scale_downs"] == 0
        assert node.alive
        scaler.stop()
    finally:
        ray_tpu.shutdown()


def test_drain_grace_expiry_forces_termination():
    """Retirement goes through the drain path: the node is marked
    draining first; if in-flight work pins its resources past the grace
    deadline, termination is forced."""
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=30.0, drain_grace_s=0.4,
            runtime=rt,
        )
        # drive step() manually: deterministic, no loop races
        rt.scheduler.fail_fast_infeasible = False

        @ray_tpu.remote(num_cpus=4)
        def big():
            return "ran"

        ref = big.remote()
        scaler.step()  # launches the node for the queued demand
        assert ray_tpu.get(ref, timeout=30) == "ran"
        node = provider.created[0]
        hex_id = node.node_id.hex()
        # in-flight work pins the node while retirement begins
        assert node.resources.try_acquire({"CPU": 1.0})
        scaler._begin_retirement(hex_id, node, "test retirement")
        assert node.draining and node.alive, "drain path, not a kill"
        scaler.step()
        assert node.alive, "grace not expired: busy draining node survives"
        time.sleep(0.5)
        scaler.step()  # grace expired -> forced termination
        assert not node.alive
        assert scaler.stats["scale_downs"] == 1
    finally:
        ray_tpu.shutdown()


def test_bookkeeping_survives_node_dying_mid_drain():
    """A managed node dying while draining is reconciled out of every
    table (no dangling idle clocks, no phantom counts) and the scaler
    keeps scaling afterwards."""
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=30.0, drain_grace_s=30.0,
            runtime=rt,
        )
        rt.scheduler.fail_fast_infeasible = False

        @ray_tpu.remote(num_cpus=4)
        def big():
            return "ran"

        ref = big.remote()
        scaler.step()
        assert ray_tpu.get(ref, timeout=30) == "ran"
        node = provider.created[0]
        node.resources.try_acquire({"CPU": 1.0})  # keep the drain open
        scaler._begin_retirement(node.node_id.hex(), node, "test retirement")
        assert node.draining
        assert scaler.status()["retiring"] == 1
        # the node dies mid-drain (spot reclaim beat the grace period)
        rt.scheduler.remove_node(node.node_id)
        scaler.step()
        status = scaler.status()
        assert status["managed_nodes"] == 0
        assert status["retiring"] == 0
        assert status["per_type"].get("cpu4", 0) == 0
        assert scaler.stats["scale_downs"] == 0  # not a policy retirement
        # and fresh demand still scales up
        ref2 = big.remote()
        scaler.step()
        assert ray_tpu.get(ref2, timeout=30) == "ran"
        assert scaler.stats["scale_ups"] == 2
    finally:
        ray_tpu.shutdown()


def test_loop_error_is_loud_once_per_type():
    """The loop must survive exceptions, but LOUDLY: every error counts,
    and each exception type emits exactly one autoscaler.error event."""
    from ray_tpu.util import state

    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.02, idle_timeout_s=5.0,
        )

        def boom():
            raise ValueError("wedged control loop")

        scaler.step = boom
        scaler.start()
        deadline = time.monotonic() + 10
        while scaler.stats["loop_errors"] < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        scaler.stop()
        assert scaler.stats["loop_errors"] >= 3
        errors = [
            e for e in state.list_events(limit=500)
            if e.get("kind") == "autoscaler.error"
            and e.get("extra", {}).get("error_type") == "ValueError"
        ]
        assert len(errors) == 1, errors
    finally:
        ray_tpu.shutdown()


def test_spot_provider_schedule_and_class_limits():
    """SpotNodeProvider labels nodes spot and reclaims them per its
    schedule through the REAL announced-preemption path; per-class
    limits cap how many spot nodes binpacking may plan."""
    from ray_tpu.core.capacity import SpotNodeProvider

    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        inner = FakeNodeProvider(rt.scheduler)
        provider = SpotNodeProvider(
            inner, schedule=[None], warning_s=0.2, seed=7
        )
        scaler = Autoscaler(
            rt.scheduler, provider,
            [NodeType("spot2", {"CPU": 2.0}, capacity_class="spot")],
            poll_interval_s=0.05, idle_timeout_s=60.0, runtime=rt,
            class_limits={"spot": 1},
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=2)
        def work():
            return "ran"

        assert ray_tpu.get(work.remote(), timeout=30) == "ran"
        node = inner.created[0]
        assert node.labels["capacity_class"] == "spot"
        assert scaler.status()["per_class"] == {"spot": 1}

        # a gang needing TWO more spot nodes is blocked by the class
        # limit (gang-atomic: no partial launch happens either)
        pg = ray_tpu.api.placement_group(
            [{"CPU": 2.0}, {"CPU": 2.0}], strategy="PACK"
        )
        deadline = time.monotonic() + 10
        while scaler.stats["blocked"] == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert scaler.stats["blocked"] >= 1
        assert len(inner.created) == 1, "no partial gang launches"
        # raising the limit unblocks the whole gang
        scaler.class_limits["spot"] = 3
        assert pg.wait_reserved(timeout=15), pg.state

        # deterministic reclaim drives the real preemption path
        provider.preempt_after(node, 0.01, warning_s=0.2)
        deadline = time.monotonic() + 10
        while not node.draining and time.monotonic() < deadline:
            time.sleep(0.02)
        assert node.draining, "reclaim must go through PREEMPTING"
        deadline = time.monotonic() + 10
        while node.alive and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not node.alive
        assert provider.num_preemptions() == 1
        scaler.stop()
    finally:
        ray_tpu.shutdown()


def test_unprovisionable_demand_fails_loudly():
    """With a scaler attached, demand NO node type can ever cover must
    raise OutOfResourcesError instead of queueing silently forever."""
    from ray_tpu.core.exceptions import OutOfResourcesError

    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    try:
        provider = FakeNodeProvider(rt.scheduler)
        scaler = Autoscaler(
            rt.scheduler, provider, [NodeType("cpu4", {"CPU": 4.0})],
            poll_interval_s=0.05, idle_timeout_s=5.0,
        )
        scaler.start()

        @ray_tpu.remote(num_cpus=64)
        def impossible():
            return "never"

        with pytest.raises(OutOfResourcesError):
            ray_tpu.get(impossible.remote(), timeout=30)
        scaler.stop()
    finally:
        ray_tpu.shutdown()
