"""Process worker pool: GIL-free tasks, process actors, crash recovery.

Reference behaviors modeled: worker reuse (worker_pool.h:228 prestarted
workers + lease reuse normal_task_submitter.cc:108), worker-death detection
and actor restart (gcs_actor_manager.h:328), runtime-env isolation in the
worker's own environment.
"""

import os
import time

import pytest

import ray_tpu as api
from ray_tpu.core.worker_pool import (
    ProcessWorkerPool,
    WorkerCrashedError,
    get_worker_pool,
)


def _square(x):
    return x * x


def _getpid():
    return os.getpid()


def _read_env(name):
    return os.environ.get(name)


def _crash():
    os._exit(42)


class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def pid(self):
        return os.getpid()

    def die(self):
        os._exit(1)


# ------------------------------------------------------------------ pool unit


def test_pool_executes_and_reuses_workers():
    pool = ProcessWorkerPool(max_workers=2)
    try:
        assert pool.execute(_square, (7,), {}) == 49
        pid1 = pool.execute(_getpid, (), {})
        pid2 = pool.execute(_getpid, (), {})
        assert pid1 == pid2  # same idle worker reused
        assert pid1 != os.getpid()  # and it is NOT this process
        assert pool.stats["spawned"] == 1
        assert pool.stats["reused"] >= 1
    finally:
        pool.shutdown()


def test_pool_env_isolation():
    pool = ProcessWorkerPool(max_workers=2)
    try:
        v = pool.execute(_read_env, ("RAY_TPU_TEST_ENV",), {},
                         env_vars={"RAY_TPU_TEST_ENV": "inside"})
        assert v == "inside"
        assert os.environ.get("RAY_TPU_TEST_ENV") is None  # parent untouched
    finally:
        pool.shutdown()


def test_pool_worker_crash_raises_and_recovers():
    pool = ProcessWorkerPool(max_workers=2)
    try:
        with pytest.raises(WorkerCrashedError):
            pool.execute(_crash, (), {})
        # pool recovers with a fresh worker
        assert pool.execute(_square, (3,), {}) == 9
        assert pool.stats["crashed"] == 1
    finally:
        pool.shutdown()


# ------------------------------------------------------------- task executor


def test_process_task_runs_in_separate_pid(runtime):
    pid_task = api.remote(_getpid).options(executor="process")
    child = api.get(pid_task.remote())
    assert child != os.getpid()


def test_process_task_gil_free_parallelism(runtime):
    """Two CPU-burn tasks across processes finish in ~1x single-task time."""

    def burn(n):
        acc = 0
        for i in range(n):
            acc += i * i
        return acc

    n = 2_000_000
    t0 = time.perf_counter()
    api.get(api.remote(burn).options(executor="process").remote(n))
    solo = time.perf_counter() - t0

    t0 = time.perf_counter()
    refs = [
        api.remote(burn).options(executor="process").remote(n) for _ in range(2)
    ]
    api.get(refs)
    duo = time.perf_counter() - t0
    # true parallelism: 2 tasks take well under 2x one task (allow slack
    # for spawn variance on a loaded CI host)
    assert duo < solo * 1.7, (solo, duo)


def test_process_task_error_propagates(runtime):
    def boom():
        raise ValueError("process boom")

    from ray_tpu.core.exceptions import TaskError

    with pytest.raises(TaskError, match="process boom"):
        api.get(api.remote(boom).options(executor="process").remote())


def test_process_task_crash_retries(runtime):
    marker = os.path.join("/tmp", f"ray_tpu_crash_{os.getpid()}")
    if os.path.exists(marker):
        os.unlink(marker)

    def crash_once(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(3)
        return "recovered"

    f = api.remote(crash_once).options(executor="process", max_retries=2,
                                       retry_exceptions=True)
    try:
        assert api.get(f.remote(marker)) == "recovered"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


# ------------------------------------------------------------ process actors


def test_process_actor_state_and_pid(runtime):
    A = api.remote(Counter).options(executor="process")
    a = A.remote(10)
    assert api.get(a.incr.remote()) == 11
    assert api.get(a.incr.remote(5)) == 16  # state persists in the child
    child_pid = api.get(a.pid.remote())
    assert child_pid != os.getpid()
    assert api.get(a.__ray_pid__.remote()) == child_pid


def test_thread_actor_pid_is_parent(runtime):
    A = api.remote(Counter)
    a = A.remote()
    assert api.get(a.__ray_pid__.remote()) == os.getpid()


def test_process_actor_crash_restarts(runtime):
    A = api.remote(Counter).options(executor="process", max_restarts=1)
    a = A.remote(0)
    assert api.get(a.incr.remote()) == 1
    from ray_tpu.core.exceptions import ActorDiedError

    with pytest.raises(ActorDiedError):
        api.get(a.die.remote())
    # restarted: fresh state, new process
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            assert api.get(a.incr.remote()) == 1
            break
        except ActorDiedError:
            time.sleep(0.1)
    else:
        raise AssertionError("actor did not restart")


def test_working_dir_runtime_env(tmp_path, runtime):
    """runtime_env working_dir (reference runtime_env plugin): the process
    worker runs with cwd = working_dir and can import files there."""
    (tmp_path / "localmod.py").write_text("MAGIC = 'from-working-dir'\n")

    @api.remote(executor="process", runtime_env={"working_dir": str(tmp_path)})
    def probe():
        import os

        import localmod  # resolvable only via the working_dir

        return os.getcwd(), localmod.MAGIC

    cwd, magic = api.get(probe.remote(), timeout=60)
    assert cwd == str(tmp_path)
    assert magic == "from-working-dir"

    # workers are keyed by working_dir: a different dir gets a fresh worker
    other = tmp_path / "other"
    other.mkdir()

    @api.remote(executor="process", runtime_env={"working_dir": str(other)})
    def where():
        import os

        return os.getcwd()

    assert api.get(where.remote(), timeout=60) == str(other)

    # thread tasks must reject working_dir loudly (process-global cwd)
    @api.remote(runtime_env={"working_dir": str(tmp_path)})
    def threaded():
        return 1

    with pytest.raises(ValueError, match="process"):
        threaded.remote()

    with pytest.raises(ValueError, match="not a directory"):
        @api.remote(executor="process",
                        runtime_env={"working_dir": "/definitely/missing"})
        def bad():
            return 1

        bad.remote()


def test_working_dir_reasserted_on_reuse(tmp_path, runtime):
    """A task's os.chdir must not leak into the next task on a reused
    worker — cwd is part of the pool's reuse contract."""
    wd = tmp_path / "wd"
    wd.mkdir()

    @api.remote(executor="process", runtime_env={"working_dir": str(wd)})
    def chdir_away():
        import os

        os.chdir("/tmp")
        return os.getcwd()

    @api.remote(executor="process", runtime_env={"working_dir": str(wd)})
    def where():
        import os

        return os.getcwd()

    assert api.get(chdir_away.remote(), timeout=60) == "/tmp"
    assert api.get(where.remote(), timeout=60) == str(wd)


def test_process_actor_runtime_env(tmp_path, runtime):
    """Process actors get env_vars + working_dir isolation (reference:
    actor-level runtime_env)."""
    wd = tmp_path / "actor_wd"
    wd.mkdir()
    (wd / "cfgmod.py").write_text("NAME = 'actor-env'\n")

    @api.remote(executor="process", max_restarts=0,
                runtime_env={"env_vars": {"MY_TOKEN": "s3cr3t"},
                             "working_dir": str(wd)})
    class Svc:
        def probe(self):
            import os

            import cfgmod

            return os.getcwd(), os.environ["MY_TOKEN"], cfgmod.NAME

    svc = Svc.remote()
    cwd, token, name = api.get(svc.probe.remote(), timeout=60)
    assert cwd == str(wd)
    assert token == "s3cr3t"
    assert name == "actor-env"
    # the driver's environment is untouched
    import os

    assert "MY_TOKEN" not in os.environ

    # thread actors reject runtime_env loudly
    @api.remote(runtime_env={"env_vars": {"X": "1"}})
    class Threaded:
        pass

    with pytest.raises(ValueError, match="process"):
        Threaded.remote()


def test_process_actor_py_modules(tmp_path, runtime):
    lib = tmp_path / "lib"
    lib.mkdir()
    (lib / "shippedmod.py").write_text("VALUE = 123\n")

    @api.remote(executor="process",
                runtime_env={"py_modules": [str(lib)]})
    class Uses:
        def val(self):
            import shippedmod

            return shippedmod.VALUE

    assert api.get(Uses.remote().val.remote(), timeout=60) == 123
