"""Multi-node scheduling, placement groups, object store behavior.

Coverage modeled on reference python/ray/tests/test_placement_group*.py and
test_scheduling*.py using the N-logical-nodes pattern (cluster_utils.py:135).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.object_store import ObjectStore, Tier
from ray_tpu.core.ids import JobID, ObjectID


def test_spread_uses_all_nodes(cluster4):
    import threading
    seen_threads = set()

    @ray_tpu.remote(num_cpus=4)
    def whereami():
        import time
        time.sleep(0.1)
        return threading.current_thread().name

    # 4 nodes x 4 cpus; 4 tasks at 4 cpus must use all four nodes.
    refs = [whereami.options(scheduling_strategy="SPREAD").remote() for _ in range(4)]
    assert len(ray_tpu.get(refs)) == 4
    assert ray_tpu.cluster_resources()["CPU"] == 16.0


def test_placement_group_pack(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK")
    assert pg.ready(timeout=5)
    nodes = {b.node.node_id for b in pg.bundles}
    assert len(nodes) == 1
    ray_tpu.remove_placement_group(pg)


def test_placement_group_strict_spread(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 2}] * 4, strategy="STRICT_SPREAD")
    nodes = {b.node.node_id for b in pg.bundles}
    assert len(nodes) == 4
    ray_tpu.remove_placement_group(pg)


def test_placement_group_infeasible(cluster4):
    from ray_tpu.core.exceptions import PlacementGroupUnschedulableError

    with pytest.raises(PlacementGroupUnschedulableError):
        ray_tpu.placement_group([{"CPU": 100}])


def test_task_in_placement_group(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 4}], strategy="PACK")

    @ray_tpu.remote(num_cpus=2)
    def inside():
        return "in-pg"

    strategy = ray_tpu.PlacementGroupSchedulingStrategy(pg, 0)
    ref = inside.options(scheduling_strategy=strategy).remote()
    assert ray_tpu.get(ref) == "in-pg"
    ray_tpu.remove_placement_group(pg)


def test_actor_in_placement_group_bundle(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")

    @ray_tpu.remote(num_cpus=2)
    class Pinned:
        def node(self):
            return "ok"

    a = Pinned.options(
        scheduling_strategy=ray_tpu.PlacementGroupSchedulingStrategy(pg, 1)
    ).remote()
    assert ray_tpu.get(a.node.remote()) == "ok"
    # Bundle 1's reservation should now be exhausted.
    assert pg.bundles[1].reserved.available()["CPU"] == 0.0
    ray_tpu.kill(a)
    ray_tpu.remove_placement_group(pg)


def test_node_affinity(cluster4):
    target = ray_tpu.nodes()[2]

    @ray_tpu.remote
    def pinned():
        return "here"

    strat = ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=cluster4.scheduler.nodes()[2].node_id
    )
    assert ray_tpu.get(pinned.options(scheduling_strategy=strat).remote()) == "here"


# ---------------------------------------------------------------- object store


def test_object_store_spill(tmp_path):
    store = ObjectStore(capacity_bytes=1 << 20, spill_dir=str(tmp_path))
    job = JobID.next()
    refs = []
    for i in range(8):
        oid = ObjectID.for_put(job)
        store.put(oid, np.full((256, 256), i, dtype=np.float32))  # 256KiB each
        refs.append(oid)
    assert store.stats["spills"] > 0
    # Everything still retrievable (restored from disk).
    for i, oid in enumerate(refs):
        assert store.get(oid)[0, 0] == i
    assert store.stats["restores"] > 0


def test_object_store_tiers():
    store = ObjectStore()
    job = JobID.next()
    small = ObjectID.for_put(job)
    store.put(small, b"tiny")
    assert store.entry(small).tier == Tier.INLINE
    big = ObjectID.for_put(job)
    store.put(big, np.zeros((1024, 1024), dtype=np.float32))
    assert store.entry(big).tier == Tier.HOST


def test_large_numpy_roundtrip(runtime):
    arr = np.random.default_rng(0).standard_normal((512, 512))
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert abs(ray_tpu.get(total.remote(ref)) - float(arr.sum())) < 1e-6


# ------------------------------------------------- label + top-k policies


def test_node_label_hard_constraint():
    """NodeLabelSchedulingStrategy(hard=...) pins to matching nodes;
    nothing matching -> OutOfResourcesError (reference
    node_label_scheduling_policy.h)."""
    import ray_tpu
    from ray_tpu.core.exceptions import OutOfResourcesError
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.scheduler import Node, NodeLabelSchedulingStrategy

    rt = ray_tpu.init(num_cpus=2, detect_accelerators=False)
    try:
        labeled = Node(
            NodeID.from_random(), {"CPU": 2.0}, labels={"zone": "us-a"}
        )
        rt.scheduler.add_node(labeled)

        @ray_tpu.remote
        def whereami():
            return "ran"

        ref = whereami.options(
            scheduling_strategy=NodeLabelSchedulingStrategy(
                hard={"zone": ["us-a", "us-b"]}
            )
        ).remote()
        assert ray_tpu.get(ref, timeout=30) == "ran"
        # it MUST have run on the labeled node
        events = [e for e in rt.task_events() if e["name"] == "whereami"]
        assert events and events[-1]["node"] == labeled.node_id.hex()

        bad = whereami.options(
            scheduling_strategy=NodeLabelSchedulingStrategy(
                hard={"zone": ["eu-west"]}
            )
        ).remote()
        with pytest.raises(OutOfResourcesError):
            ray_tpu.get(bad, timeout=30)
    finally:
        ray_tpu.shutdown()


def test_node_label_soft_preference():
    import ray_tpu
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.scheduler import Node, NodeLabelSchedulingStrategy

    rt = ray_tpu.init(num_cpus=2, detect_accelerators=False)
    try:
        fast = Node(NodeID.from_random(), {"CPU": 2.0}, labels={"disk": "ssd"})
        rt.scheduler.add_node(fast)

        @ray_tpu.remote
        def f():
            return 1

        ref = f.options(
            scheduling_strategy=NodeLabelSchedulingStrategy(
                soft={"disk": ["ssd"]}
            )
        ).remote()
        assert ray_tpu.get(ref, timeout=30) == 1
        events = [e for e in rt.task_events() if e["name"] == "f"]
        assert events[-1]["node"] == fast.node_id.hex()

        # soft miss still schedules (falls back to any node)
        ref2 = f.options(
            scheduling_strategy=NodeLabelSchedulingStrategy(
                soft={"disk": ["nvme"]}
            )
        ).remote()
        assert ray_tpu.get(ref2, timeout=30) == 1
    finally:
        ray_tpu.shutdown()


def test_hybrid_top_k_randomizes_over_idle_nodes():
    """The hybrid policy picks among the top-k candidates, not always
    the same node (reference hybrid_scheduling_policy.h top-k)."""
    import ray_tpu
    from ray_tpu.core.ids import TaskID
    from ray_tpu.core.scheduler import TaskSpec

    rt = ray_tpu.init(num_cpus=2, num_nodes=4, detect_accelerators=False)
    try:
        spec = TaskSpec(
            task_id=TaskID.of(rt.job_id), name="probe", func=lambda: None,
            args=(), kwargs={}, resources={"CPU": 1.0},
        )
        chosen = {
            rt.scheduler._pick_node(spec).node_id.hex() for _ in range(40)
        }
        assert len(chosen) >= 2, "top-k hybrid never varied its pick"
    finally:
        ray_tpu.shutdown()


def test_tpu_pod_env_resources(monkeypatch):
    """TPU pod env vars drive resource synthesis: visible chips count,
    and the slice head resource appears only on worker 0 (reference
    accelerators/tpu.py:109, :375)."""
    from ray_tpu.core.resources import detect_tpu_resources

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-16")
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.delenv("TPU_TOPOLOGY", raising=False)
    res = detect_tpu_resources()
    assert res["TPU"] == 4.0
    assert res["TPU-v4-16-head"] == 1.0

    # worker 1 of the same slice: chips, but NO head resource
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    res = detect_tpu_resources()
    assert res["TPU"] == 4.0
    assert "TPU-v4-16-head" not in res

    # type-only (no visible chips): v4-16 = 16 TensorCores = 8 chips,
    # split over 2 hosts -> 4 chips each
    monkeypatch.delenv("TPU_VISIBLE_CHIPS")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    res = detect_tpu_resources()
    assert res["TPU"] == 4.0
    assert res["TPU-v4-16-head"] == 1.0

    # chip-counting generation: v5litepod-8 = 8 chips over 2 hosts
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-8")
    res = detect_tpu_resources()
    assert res["TPU"] == 4.0
    assert res["TPU-v5litepod-8-head"] == 1.0

    # a SMALLER attached topology clamps the type-derived count: a
    # v5litepod-4 slice type with a 1x1 topology is ONE real chip
    # (tunneled dev chips / GKE subslicing) — over-reporting would let
    # 4 num_tpus=1 tasks contend for it. A clamped node is a SUB-slice:
    # it must NOT advertise the full-slice head resource, or a gang
    # demanding the slice lands on fewer chips than it asked for.
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-4")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setenv("TPU_TOPOLOGY", "1x1")
    res = detect_tpu_resources()
    assert res["TPU"] == 1.0
    assert "TPU-v5litepod-4-head" not in res
    # ...but topology never INFLATES past the type-derived count, and a
    # full-slice topology keeps the head resource
    monkeypatch.setenv("TPU_TOPOLOGY", "4x4")
    res = detect_tpu_resources()
    assert res["TPU"] == 4.0
    assert res["TPU-v5litepod-4-head"] == 1.0

    # multi-host sub-slice: topology counts chips SLICE-WIDE, so the
    # clamp divides by the host count — v4-32 type (8 chips/host over 2
    # hosts) with an attached 2x2x2 = 8-chip topology is 4 real
    # chips/host, not 8
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    monkeypatch.setenv("TPU_TOPOLOGY", "2x2x2")
    res = detect_tpu_resources()
    assert res["TPU"] == 4.0
    assert "TPU-v4-32-head" not in res

    # the clamp applies to the VISIBLE-chips path too: a container shown
    # 4 chips on a node whose attached topology is 1x1 has one real chip
    # and is a sub-slice (no head resource)
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-4")
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setenv("TPU_TOPOLOGY", "1x1")
    res = detect_tpu_resources()
    assert res["TPU"] == 1.0
    assert "TPU-v5litepod-4-head" not in res


def test_task_threads_are_reused():
    """Thread-executor tasks run on pooled, reused threads — a burst of
    sequential tasks must not spawn a thread per task (VERDICT r3 weak
    #6), while concurrency stays gated by resources, not thread count."""
    import ray_tpu

    rt = ray_tpu.init(num_cpus=2, detect_accelerators=False)
    try:
        @ray_tpu.remote
        def ident():
            import threading as _t

            return id(_t.current_thread())

        idents = set()
        for _ in range(40):
            idents.add(ray_tpu.get(ident.remote(), timeout=30))
        assert len(idents) <= 4, f"{len(idents)} distinct threads for 40 tasks"
        assert rt.scheduler._task_threads._spawned <= 6
    finally:
        ray_tpu.shutdown()
