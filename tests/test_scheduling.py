"""Multi-node scheduling, placement groups, object store behavior.

Coverage modeled on reference python/ray/tests/test_placement_group*.py and
test_scheduling*.py using the N-logical-nodes pattern (cluster_utils.py:135).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.object_store import ObjectStore, Tier
from ray_tpu.core.ids import JobID, ObjectID


def test_spread_uses_all_nodes(cluster4):
    import threading
    seen_threads = set()

    @ray_tpu.remote(num_cpus=4)
    def whereami():
        import time
        time.sleep(0.1)
        return threading.current_thread().name

    # 4 nodes x 4 cpus; 4 tasks at 4 cpus must use all four nodes.
    refs = [whereami.options(scheduling_strategy="SPREAD").remote() for _ in range(4)]
    assert len(ray_tpu.get(refs)) == 4
    assert ray_tpu.cluster_resources()["CPU"] == 16.0


def test_placement_group_pack(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_PACK")
    assert pg.ready(timeout=5)
    nodes = {b.node.node_id for b in pg.bundles}
    assert len(nodes) == 1
    ray_tpu.remove_placement_group(pg)


def test_placement_group_strict_spread(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 2}] * 4, strategy="STRICT_SPREAD")
    nodes = {b.node.node_id for b in pg.bundles}
    assert len(nodes) == 4
    ray_tpu.remove_placement_group(pg)


def test_placement_group_infeasible(cluster4):
    from ray_tpu.core.exceptions import PlacementGroupUnschedulableError

    with pytest.raises(PlacementGroupUnschedulableError):
        ray_tpu.placement_group([{"CPU": 100}])


def test_task_in_placement_group(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 4}], strategy="PACK")

    @ray_tpu.remote(num_cpus=2)
    def inside():
        return "in-pg"

    strategy = ray_tpu.PlacementGroupSchedulingStrategy(pg, 0)
    ref = inside.options(scheduling_strategy=strategy).remote()
    assert ray_tpu.get(ref) == "in-pg"
    ray_tpu.remove_placement_group(pg)


def test_actor_in_placement_group_bundle(cluster4):
    pg = ray_tpu.placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")

    @ray_tpu.remote(num_cpus=2)
    class Pinned:
        def node(self):
            return "ok"

    a = Pinned.options(
        scheduling_strategy=ray_tpu.PlacementGroupSchedulingStrategy(pg, 1)
    ).remote()
    assert ray_tpu.get(a.node.remote()) == "ok"
    # Bundle 1's reservation should now be exhausted.
    assert pg.bundles[1].reserved.available()["CPU"] == 0.0
    ray_tpu.kill(a)
    ray_tpu.remove_placement_group(pg)


def test_node_affinity(cluster4):
    target = ray_tpu.nodes()[2]

    @ray_tpu.remote
    def pinned():
        return "here"

    strat = ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=cluster4.scheduler.nodes()[2].node_id
    )
    assert ray_tpu.get(pinned.options(scheduling_strategy=strat).remote()) == "here"


# ---------------------------------------------------------------- object store


def test_object_store_spill(tmp_path):
    store = ObjectStore(capacity_bytes=1 << 20, spill_dir=str(tmp_path))
    job = JobID.next()
    refs = []
    for i in range(8):
        oid = ObjectID.for_put(job)
        store.put(oid, np.full((256, 256), i, dtype=np.float32))  # 256KiB each
        refs.append(oid)
    assert store.stats["spills"] > 0
    # Everything still retrievable (restored from disk).
    for i, oid in enumerate(refs):
        assert store.get(oid)[0, 0] == i
    assert store.stats["restores"] > 0


def test_object_store_tiers():
    store = ObjectStore()
    job = JobID.next()
    small = ObjectID.for_put(job)
    store.put(small, b"tiny")
    assert store.entry(small).tier == Tier.INLINE
    big = ObjectID.for_put(job)
    store.put(big, np.zeros((1024, 1024), dtype=np.float32))
    assert store.entry(big).tier == Tier.HOST


def test_large_numpy_roundtrip(runtime):
    arr = np.random.default_rng(0).standard_normal((512, 512))
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert abs(ray_tpu.get(total.remote(ref)) - float(arr.sum())) < 1e-6
