"""Serve-layer overload & failure resilience drills.

Chaos-driven coverage for the resilience tentpole: end-to-end deadlines
(typed RequestTimeoutError, engine slot cancellation), router retry/
failover onto a different live replica, admission control with load
shedding (BackPressureError → HTTP 429 + Retry-After), graceful replica
draining, RPC-layer chaos injection, and the 200-request capstone drill
(replica killed mid-run + injected call failures, zero hung requests).
"""

import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core import chaos
from ray_tpu.core.chaos import ChaosInjectedError
from ray_tpu.core.exceptions import (
    BackPressureError,
    ReplicaDrainingError,
    RequestTimeoutError,
    TaskError,
    unwrap_error,
)


@pytest.fixture(autouse=True)
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    chaos.clear_chaos()
    serve.shutdown()
    ray_tpu.shutdown()


# shared blocking gates: replicas run in-process, so module-level Events
# are visible to deployment instances without arg plumbing
_GATES = {}


def _gate(name: str) -> threading.Event:
    return _GATES.setdefault(name, threading.Event())


# ------------------------------------------------------------------ deadlines


def test_deadline_fails_fast_with_typed_error():
    @serve.deployment
    class Sleepy:
        def __call__(self, payload):
            time.sleep(5.0)
            return payload

    handle = serve.run(Sleepy.options(name="sleepy").bind())
    t0 = time.time()
    ref = handle.options(timeout_s=0.3).remote("x")
    with pytest.raises(RequestTimeoutError):
        ray_tpu.get(ref, timeout=10)
    # fail-fast: the typed error lands near the deadline, not after the
    # replica's 5s sleep finishes
    assert time.time() - t0 < 3.0


def test_deadline_propagates_to_replica_context():
    from ray_tpu.serve import context as serve_ctx

    @serve.deployment
    class Probe:
        def __call__(self, payload):
            return serve_ctx.get_request_deadline()

    handle = serve.run(Probe.options(name="probe").bind())
    # no deadline configured -> ambient deadline is None
    assert ray_tpu.get(handle.remote("x"), timeout=10) is None
    deadline = ray_tpu.get(
        handle.options(timeout_s=30).remote("x"), timeout=10
    )
    assert deadline is not None and deadline - time.time() < 31


def test_deadline_cancels_engine_slot():
    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    config = get_config("gpt2-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = LLMEngine(config, params, EngineConfig(max_slots=2))
    try:
        budget = engine.max_seq - 8
        stream = engine.submit(
            [1, 2, 3], max_tokens=budget, deadline_ts=time.time() + 0.4
        )
        with pytest.raises(RequestTimeoutError):
            stream.result(timeout=30)
        # the slot was evicted, not left generating into the void
        deadline = time.time() + 5
        while time.time() < deadline:
            if all(s.free for s in engine.slots):
                break
            time.sleep(0.05)
        assert all(s.free for s in engine.slots)
        assert engine.metrics["timeouts"] >= 1
        # an already-expired deadline fails at submit, before queueing
        with pytest.raises(RequestTimeoutError):
            engine.submit([1, 2, 3], max_tokens=4,
                          deadline_ts=time.time() - 1)
    finally:
        engine.shutdown()


def test_paged_engine_deadline_evicts_slot():
    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm import PagedEngineConfig, PagedLLMEngine

    config = get_config("gpt2-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = PagedLLMEngine(config, params, PagedEngineConfig(max_slots=2))
    try:
        budget = engine.paged.max_slot_tokens - 8
        stream = engine.submit(
            [1, 2, 3], max_tokens=budget, deadline_ts=time.time() + 0.4
        )
        with pytest.raises(RequestTimeoutError):
            stream.result(timeout=30)
        deadline = time.time() + 5
        while time.time() < deadline:
            if all(s.free for s in engine.slots):
                break
            time.sleep(0.05)
        assert all(s.free for s in engine.slots)
        assert engine.metrics["timeouts"] >= 1
    finally:
        engine.shutdown()


# ----------------------------------------------------------- retry/failover


def test_router_fails_over_when_replica_dies_mid_request():
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Slow:
        def __call__(self, payload):
            time.sleep(0.3)
            return f"ok-{payload}"

    handle = serve.run(Slow.options(name="failover").bind())
    refs = [handle.options(timeout_s=30).remote(i) for i in range(8)]
    # kill one replica while its requests are mid-sleep: the router must
    # re-pick the surviving replica for every failed attempt
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["failover"]
    time.sleep(0.05)
    ray_tpu.kill(state.replicas[0])
    results = ray_tpu.get(refs, timeout=60)
    assert results == [f"ok-{i}" for i in range(8)]


def test_stream_fails_over_when_replica_killed_mid_stream():
    @serve.deployment(num_replicas=2)
    class Streamer:
        def stream(self, payload):
            for i in range(10):
                time.sleep(0.05)
                yield i

    handle = serve.run(Streamer.options(name="streamer").bind())
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["streamer"]
    stream = handle.options(stream=True, timeout_s=60).stream.remote("x")
    got = []
    it = iter(stream)
    for _ in range(2):
        got.append(ray_tpu.get(next(it), timeout=30))
    # kill whichever replica is producing: the feeder must fail over to
    # the survivor, replay the generator, and skip the delivered prefix
    ongoing = {
        state.replica_set._key(r): r for r in state.replicas
    }
    busy = [
        r for k, r in ongoing.items()
        if state.replica_set.ongoing_for(k) > 0
    ]
    assert busy, "no replica shows the in-flight stream"
    ray_tpu.kill(busy[0])
    for ref in it:
        got.append(ray_tpu.get(ref, timeout=30))
    assert got == list(range(10)), got


def test_reaper_releases_ongoing_on_error():
    @serve.deployment
    class Boom:
        def __call__(self, payload):
            raise ValueError("user error: not retryable")

    handle = serve.run(Boom.options(name="boom").bind())
    refs = [handle.remote(i) for i in range(4)]
    for ref in refs:
        with pytest.raises(TaskError):
            ray_tpu.get(ref, timeout=10)
    state_set = serve.get_handle("boom")._set
    deadline = time.time() + 5
    while time.time() < deadline and state_set.total_ongoing() > 0:
        time.sleep(0.05)
    # errored refs must release their ongoing counts or every failure
    # would permanently skew least-loaded picks
    assert state_set.total_ongoing() == 0


def test_user_errors_are_not_retried():
    calls = {"n": 0}

    @serve.deployment
    class Once:
        def __call__(self, payload):
            calls["n"] += 1
            raise ValueError("deterministic app failure")

    handle = serve.run(Once.options(name="once").bind())
    with pytest.raises(TaskError):
        ray_tpu.get(handle.remote("x"), timeout=10)
    time.sleep(0.3)  # any (buggy) retry would have landed by now
    assert calls["n"] == 1


# -------------------------------------------------------- admission control


def test_admission_control_sheds_then_recovers():
    gate = _gate("shed")
    gate.clear()

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=1)
    class Gated:
        def __call__(self, payload):
            gate.wait(timeout=30)
            return f"done-{payload}"

    handle = serve.run(Gated.options(name="gated").bind())
    admitted = [handle.options(timeout_s=30).remote(i) for i in range(2)]
    time.sleep(0.1)
    # capacity (1x1) + queue (1) is full: the next request sheds
    # synchronously with the typed error
    with pytest.raises(BackPressureError):
        handle.remote("overflow")
    gate.set()
    results = ray_tpu.get(admitted, timeout=30)
    assert results == ["done-0", "done-1"]
    # load drained: admission recovers
    assert ray_tpu.get(handle.remote("again"), timeout=30) == "done-again"


def test_http_proxy_maps_backpressure_to_429():
    gate = _gate("http429")
    gate.clear()

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=0)
    class Busy:
        def __call__(self, payload):
            gate.wait(timeout=30)
            return "ok"

    serve.run(Busy.options(name="busy").bind())
    port = serve.start_http()
    blocked = serve.get_handle("busy").options(timeout_s=30).remote("x")
    time.sleep(0.1)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/busy", data=b'"y"',
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 429
    assert e.value.headers.get("Retry-After") == "1"
    gate.set()
    assert ray_tpu.get(blocked, timeout=30) == "ok"


def test_openai_maps_typed_errors_to_http_status():
    from ray_tpu.serve.llm.openai import OpenAIFrontend

    state = {"n": 0}

    @serve.deployment
    class FlakyLLM:
        def generate(self, payload):
            state["n"] += 1
            if state["n"] == 1:
                raise BackPressureError("engine admit queue is full")
            if state["n"] == 2:
                raise RequestTimeoutError("deadline exceeded")
            tokens = [104, 105]  # "hi"
            return {"tokens": tokens, "usage": {
                "prompt_tokens": 1, "completion_tokens": 2,
                "total_tokens": 3,
            }}

    serve.run(FlakyLLM.options(name="flaky-llm").bind())
    frontend = OpenAIFrontend({"flaky": "flaky-llm"})
    try:
        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{frontend.port}/v1/completions",
                data=b'{"model": "flaky", "prompt": "x", "max_tokens": 2}',
                headers={"Content-Type": "application/json"},
            )
            return urllib.request.urlopen(req, timeout=30)

        # overload -> 429 with Retry-After, then deadline -> 504, then 200
        with pytest.raises(urllib.error.HTTPError) as e:
            post()
        assert e.value.code == 429
        assert e.value.headers.get("Retry-After") == "1"
        with pytest.raises(urllib.error.HTTPError) as e:
            post()
        assert e.value.code == 504
        import json as _json

        body = _json.loads(post().read())
        assert body["choices"][0]["text"] == "hi"
    finally:
        frontend.stop()


def test_engine_admission_bound_sheds():
    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm import EngineConfig, LLMEngine

    config = get_config("gpt2-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    # 1 slot, 1 queued: the third concurrent submit must shed
    engine = LLMEngine(
        config, params, EngineConfig(max_slots=1, max_queued_requests=1)
    )
    try:
        budget = engine.max_seq - 8
        first = engine.submit([1, 2, 3], max_tokens=budget)
        time.sleep(0.3)  # let it take the slot
        second = engine.submit([1, 2, 3], max_tokens=4)
        with pytest.raises(BackPressureError):
            engine.submit([1, 2, 3], max_tokens=4)
        assert engine.metrics["shed"] >= 1
    finally:
        engine.shutdown()


# ------------------------------------------------------------------ draining


def test_drain_completes_inflight_before_kill():
    gate = _gate("drain")
    gate.clear()

    @serve.deployment(num_replicas=2, max_ongoing_requests=2,
                      drain_timeout_s=20.0)
    class Draining:
        def __call__(self, payload):
            gate.wait(timeout=30)
            return f"finished-{payload}"

    handle = serve.run(Draining.options(name="drainer").bind())
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["drainer"]
    # both replicas must be READY (probed healthy) — scale-down only
    # drains ready replicas; unready ones are killed outright
    deadline = time.time() + 15
    while time.time() < deadline and len(state.ready_at) < 2:
        time.sleep(0.05)
    assert len(state.ready_at) >= 2
    # one in-flight request on each replica (pow-2 picks the idle one)
    refs = [handle.options(timeout_s=60).remote(i) for i in range(2)]
    time.sleep(0.2)
    state.target_replicas = 1  # scale down: newest replica must DRAIN
    deadline = time.time() + 10
    while time.time() < deadline:
        if serve.status()["drainer"]["draining_replicas"] == 1:
            break
        time.sleep(0.05)
    assert serve.status()["drainer"]["draining_replicas"] == 1
    # in-flight work is NOT dead: release the gate, both requests finish
    gate.set()
    results = sorted(ray_tpu.get(refs, timeout=30))
    assert results == ["finished-0", "finished-1"]
    # once drained, the replica is reaped
    deadline = time.time() + 10
    while time.time() < deadline:
        st = serve.status()["drainer"]
        if st["draining_replicas"] == 0 and st["live_replicas"] == 1:
            break
        time.sleep(0.1)
    st = serve.status()["drainer"]
    assert st["draining_replicas"] == 0 and st["live_replicas"] == 1


def test_draining_replica_bounces_new_calls():
    from ray_tpu.serve.controller import _ReplicaWrapper

    class Echo:
        def __call__(self, payload):
            return payload

    wrapper = _ReplicaWrapper(Echo, (), {})
    assert wrapper.call("__call__", "x") == "x"
    wrapper.prepare_drain()
    with pytest.raises(ReplicaDrainingError):
        wrapper.call("__call__", "x")


# ----------------------------------------------------------------- rpc chaos


def test_rpc_chaos_error_injection_is_retried():
    from ray_tpu.core.rpc import RpcClient, RpcServer

    calls = {"n": 0}

    def handler():
        calls["n"] += 1
        return calls["n"]

    server = RpcServer({"hit": handler})
    try:
        chaos.set_chaos(rpc_error_prob=1.0, max_injections=2, seed=1)
        client = RpcClient(server.url, retries=4, retry_wait_s=0.01)
        # two injected pre-send transport errors, then the real call:
        # the handler runs exactly once (injections never reach the wire)
        assert client.call("hit") == 1
        assert calls["n"] == 1
        assert chaos.num_injected() == 2
        client.close()
    finally:
        chaos.clear_chaos()
        server.stop()


def test_rpc_chaos_connection_drop_reconnects():
    from ray_tpu.core.rpc import RpcClient, RpcServer

    server = RpcServer({"val": lambda: 7})
    try:
        client = RpcClient(server.url, retries=2, retry_wait_s=0.01)
        assert client.call("val") == 7  # warm the persistent connection
        chaos.set_chaos(rpc_drop_prob=1.0, max_injections=1, seed=2)
        assert client.call("val") == 7  # dropped, reconnected, served
        assert chaos.num_injected() == 1
        client.close()
    finally:
        chaos.clear_chaos()
        server.stop()


def test_rpc_fully_sent_frame_is_not_retried():
    """Non-idempotent safety: a server that dies AFTER receiving the
    frame (fresh connection, zero reply bytes) must not trigger a
    resend — the handler may have executed."""
    import socket
    import struct

    conns = {"n": 0}

    def one_shot_server(sock):
        while True:
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            conns["n"] += 1
            try:
                hdr = conn.recv(8)
                if len(hdr) == 8:
                    (length,) = struct.Struct(">Q").unpack(hdr)
                    got = 0
                    while got < length:
                        chunk = conn.recv(min(65536, length - got))
                        if not chunk:
                            break
                        got += len(chunk)
            finally:
                conn.close()  # frame consumed, no reply: simulated death

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    port = lsock.getsockname()[1]
    t = threading.Thread(target=one_shot_server, args=(lsock,), daemon=True)
    t.start()
    try:
        from ray_tpu.core.rpc import RpcClient, RpcError

        client = RpcClient(f"127.0.0.1:{port}", retries=3, retry_wait_s=0.01,
                           timeout=5.0)
        with pytest.raises(RpcError, match="not retried"):
            client.call("anything")
        assert conns["n"] == 1, "fully-sent frame was resent"
        client.close()
    finally:
        lsock.close()


# ------------------------------------------------------------ static checker


def test_typed_errors_static_check():
    """scripts/check_typed_errors.py is now a shim over the raylint
    typed-errors rule; the repo-wide gate runs ONCE in
    tests/test_raylint.py. Here: the shim's compat API still flags a
    bad tree, not just passes everything."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "check_typed_errors.py"
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location("cte", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with tempfile.TemporaryDirectory() as tmp:
        bad = pathlib.Path(tmp) / "serve"
        bad.mkdir()
        (bad / "oops.py").write_text(
            "try:\n    x = 1\nexcept:\n    pass\n"
        )
        errors = mod.check_bare_except(bad)
        assert len(errors) == 1 and "bare" in errors[0]


# ------------------------------------------------------------ capstone drill


def test_chaos_drill_200_requests_no_hangs():
    """Acceptance drill: with call-failure injection armed and a replica
    killed mid-run, a 200-request load completes with ZERO hung requests —
    every request either succeeds (possibly after failover) or fails fast
    with a typed timeout/backpressure error."""
    @serve.deployment(num_replicas=3, max_ongoing_requests=8)
    class Drill:
        def __call__(self, payload):
            time.sleep(0.01)
            return payload * 2

    handle = serve.run(Drill.options(name="drill").bind())
    # wait for all replicas to be routable so the kill below leaves two
    deadline = time.time() + 15
    while time.time() < deadline:
        if serve.status()["drill"]["live_replicas"] == 3:
            break
        time.sleep(0.05)
    # arm chaos on replica CALLS only (".call" spares health probes):
    # ~15% of calls fail like real faults, bounded to 30 injections
    chaos.set_chaos(failure_prob=0.15, max_injections=30,
                    name_filter=".call", seed=7)
    caller = handle.options(timeout_s=30, max_retries=6)
    refs = [caller.remote(i) for i in range(100)]
    # kill a replica mid-run: its in-flight requests must fail over
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["drill"]
    ray_tpu.kill(state.replicas[1])
    refs += [caller.remote(i) for i in range(100, 200)]
    ok, typed_fail, hung = 0, 0, []
    for i, ref in enumerate(refs):
        try:
            assert ray_tpu.get(ref, timeout=60) == i * 2
            ok += 1
        except ray_tpu.GetTimeoutError:
            hung.append(i)
        except Exception as e:  # noqa: BLE001 - drill classification
            cause = unwrap_error(e)
            assert isinstance(
                cause, (RequestTimeoutError, BackPressureError,
                        ChaosInjectedError)
            ), f"request {i} failed with untyped {cause!r}"
            typed_fail += 1
    assert not hung, f"hung requests: {hung}"
    assert ok >= 190, (ok, typed_fail)
    assert chaos.num_injected() > 0, "drill never injected a fault"
    chaos.clear_chaos()
    # the killed replica is replaced and the deployment still serves
    assert ray_tpu.get(handle.remote(7), timeout=30) == 14
