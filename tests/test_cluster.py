"""Multi-process cluster composition tests (reference test model:
python/ray/tests/test_multi_node*.py over cluster_utils.Cluster).

These spawn REAL worker-agent OS processes that join the head over RPC:
the cluster view, remote dispatch, wire object transfer, and node-death
failover are all exercised end to end.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    """Head (2 CPUs, in-process) + 2 worker agents (2 CPUs each)."""
    c = Cluster(
        head_node_args={
            "num_cpus": 2,
            "_system_config": {"node_stale_s": 2.5, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()  # _system_config overrides must not leak across tests


def test_cluster_resources_union(cluster):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU", 0) == 6.0
    assert len(cluster.runtime.scheduler.nodes()) == 3
    infos = cluster.runtime.cluster.nodes()
    assert len(infos) == 3
    assert sum(1 for i in infos if i["is_head"]) == 1


def test_remote_task_executes_on_agent(cluster):
    import os

    @ray_tpu.remote(num_cpus=1)
    def whoami():
        return os.getpid()

    # 6 concurrent 1-CPU tasks > the head's 2 CPUs: some MUST land on
    # agents. Hold each task briefly so they overlap.
    @ray_tpu.remote(num_cpus=1)
    def hold_pid():
        time.sleep(0.5)
        return os.getpid()

    pids = set(ray_tpu.get([hold_pid.remote() for _ in range(6)], timeout=60))
    assert len(pids) >= 2, f"all tasks ran in one process: {pids}"
    assert os.getpid() in pids or len(pids) >= 2


def test_node_affinity_targets_remote_agent(cluster):
    import os

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [
        n for n in cluster.runtime.scheduler.nodes() if n.is_remote
    ]
    assert len(remote_nodes) == 2

    @ray_tpu.remote
    def whoami():
        return os.getpid()

    target = remote_nodes[0]
    pid = ray_tpu.get(
        whoami.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(target.node_id)
        ).remote(),
        timeout=60,
    )
    assert pid != os.getpid()
    # and it ran in THAT node's process, not the other agent's
    info = next(
        (rec for rec in cluster.runtime.cluster.nodes()
         if rec["node_id"] == target.node_id.hex()),
        None,
    )
    assert info is not None and info["pid"] == pid


def test_large_result_pulled_over_wire(cluster):
    """A big result stays on the agent; get() pulls it via the transfer
    plane (REMOTE tier fetch-through)."""
    from ray_tpu.core.object_store import Tier
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    def big():
        return np.arange(1_000_000, dtype=np.float64)  # 8 MB >> inline cutoff

    ref = big.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(remote_nodes[0].node_id)
    ).remote()
    # the placeholder must be REMOTE before the first get touches it
    deadline = time.monotonic() + 60
    entry = cluster.runtime.object_store.entry(ref.object_id)
    while not entry.event.is_set() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert entry.tier == Tier.REMOTE
    value = ray_tpu.get(ref, timeout=60)
    assert value.shape == (1_000_000,)
    assert float(value[12345]) == 12345.0
    # cached locally now
    assert entry.tier != Tier.REMOTE


def test_objectref_arg_roundtrip(cluster):
    """ObjectRef args resolve at the owner and ship by value; results
    chain across processes."""

    @ray_tpu.remote(num_cpus=1)
    def produce():
        return np.ones(4096, dtype=np.float32)

    @ray_tpu.remote(num_cpus=1)
    def consume(x):
        return float(x.sum())

    refs = [produce.remote() for _ in range(4)]
    outs = ray_tpu.get([consume.remote(r) for r in refs], timeout=60)
    assert outs == [4096.0] * 4


def test_remote_task_error_propagates(cluster):
    from ray_tpu.core.exceptions import TaskError
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    def boom():
        raise ValueError("remote kaboom")

    ref = boom.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(remote_nodes[0].node_id)
    ).remote()
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(ref, timeout=60)
    assert "remote kaboom" in str(ei.value)
    assert isinstance(ei.value.cause, ValueError)


def test_agent_kill_fails_over(cluster):
    """SIGKILL an agent mid-task: the task resubmits (system-failure
    budget) and completes elsewhere."""
    import os

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(1.5)
        return os.getpid()

    # saturate the cluster so agents certainly hold tasks
    refs = [slow.remote() for _ in range(6)]
    time.sleep(0.4)  # let dispatch land
    victim = cluster._nodes[0]
    cluster.remove_node(victim, allow_graceful=False)
    pids = ray_tpu.get(refs, timeout=120)
    assert len(pids) == 6
    assert all(isinstance(p, int) for p in pids)
    # the dead agent dropped out of the scheduler view
    deadline = time.monotonic() + 30
    while len(cluster.runtime.scheduler.nodes()) > 2 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert len(cluster.runtime.scheduler.nodes()) == 2


def test_graceful_remove_deregisters(cluster):
    victim = cluster._nodes[1]
    cluster.remove_node(victim, allow_graceful=True)
    deadline = time.monotonic() + 30
    while len(cluster.runtime.scheduler.nodes()) > 2 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert len(cluster.runtime.scheduler.nodes()) == 2
    # remaining capacity still works
    @ray_tpu.remote
    def f():
        return 7

    assert ray_tpu.get(f.remote(), timeout=60) == 7


def test_streaming_generator_on_remote_agent(cluster):
    """num_returns="streaming" tasks dispatch to agents: each yield
    flows back over the stream_item plane as it is produced (reference:
    ObjectRefStream across workers, core_worker.h:273)."""
    import os

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    def gen(n):
        import os as _os

        for i in range(n):
            yield (i, _os.getpid())

    stream = gen.options(
        num_returns="streaming",
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            remote_nodes[0].node_id
        ),
    ).remote(5)
    items = [ray_tpu.get(r, timeout=60) for r in stream]
    assert [i for i, _ in items] == [0, 1, 2, 3, 4]
    pids = {p for _, p in items}
    assert pids and os.getpid() not in pids, "generator ran in-process"


def test_streaming_remote_big_items_and_backpressure(cluster):
    """Big yields stay on the agent as placeholders pulled on get();
    stream_max_backlog paces a fast remote producer."""
    import time as _time

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    def gen():
        for i in range(6):
            yield np.full(200_000, i, dtype=np.float64)  # 1.6 MB each

    stream = gen.options(
        num_returns="streaming", stream_max_backlog=2,
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            remote_nodes[0].node_id
        ),
    ).remote()
    seen = []
    for ref in stream:
        _time.sleep(0.05)  # slow consumer: the producer must be paced
        seen.append(float(ray_tpu.get(ref, timeout=60)[0]))
    assert seen == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_rpc_auth_token_required():
    """A tokenless client must be dropped before any unpickling."""
    from ray_tpu.core.rpc import RpcAuthError, RpcClient, RpcError, RpcServer

    server = RpcServer({"ping": lambda: "ok"}, token="sekrit")
    try:
        good = RpcClient(server.url, token="sekrit", timeout=5.0)
        assert good.call("ping") == "ok"
        good.close()

        bad = RpcClient(server.url, token="wrong", timeout=5.0, retries=0)
        with pytest.raises(RpcAuthError):
            bad.call("ping")
        bad.close()

        none = RpcClient(server.url, timeout=5.0, retries=0)
        with pytest.raises(RpcError):
            none.call("ping")
        none.close()
    finally:
        server.stop()


# ------------------------------------------------------------- remote actors


def test_remote_actor_on_agent(cluster):
    """An actor pinned to a remote node executes THERE, keeps state
    across ordered method calls, and dies cleanly on kill."""
    import os

    from ray_tpu.core.actors import ActorState
    from ray_tpu.core.exceptions import ActorDiedError
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.value = start

        def add(self, n):
            self.value += n
            return self.value

        def pid(self):
            import os as _os

            return _os.getpid()

    target = remote_nodes[0]
    counter = Counter.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(target.node_id)
    ).remote(100)
    # ordered stateful calls across the wire
    refs = [counter.add.remote(1) for _ in range(5)]
    assert ray_tpu.get(refs, timeout=60) == [101, 102, 103, 104, 105]
    pid = ray_tpu.get(counter.pid.remote(), timeout=60)
    assert pid != os.getpid()
    info = next(
        rec for rec in cluster.runtime.cluster.nodes()
        if rec["node_id"] == target.node_id.hex()
    )
    assert info["pid"] == pid
    assert counter.state() == ActorState.ALIVE

    ray_tpu.kill(counter)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(counter.add.remote(1), timeout=60)
    assert counter.state() == ActorState.DEAD


def test_remote_actor_spillover_and_named_lookup(cluster):
    """Default placement spills to an agent when only IT has the
    resources; the name resolves cluster-wide."""
    victim_free = None

    @ray_tpu.remote(resources={"accel": 1})
    class Worker:
        def where(self):
            import os as _os

            return _os.getpid()

    # no local node has "accel": only the dedicated agent can host it
    cluster.add_node(num_cpus=1, resources={"accel": 2},
                     system_config={"node_heartbeat_s": 0.2})
    cluster.wait_for_nodes(4)
    w = Worker.options(name="accel-worker").remote()
    import os

    pid = ray_tpu.get(w.where.remote(), timeout=60)
    assert pid != os.getpid()

    # named lookup returns a live handle to the same actor
    again = ray_tpu.get_actor("accel-worker")
    assert ray_tpu.get(again.where.remote(), timeout=60) == pid


def test_remote_actor_error_and_node_death(cluster):
    """User exceptions cross the wire; killing the hosting agent fails
    pending and future calls with ActorDiedError."""
    import time as _time

    from ray_tpu.core.exceptions import ActorDiedError, TaskError
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    class Flaky:
        def boom(self):
            raise RuntimeError("actor kaboom")

        def slow(self):
            _time.sleep(5.0)
            return "done"

    target = remote_nodes[0]
    actor = Flaky.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(target.node_id)
    ).remote()
    with pytest.raises((TaskError, RuntimeError), match="actor kaboom"):
        ray_tpu.get(actor.boom.remote(), timeout=60)

    pending = actor.slow.remote()
    _time.sleep(0.5)  # let the call land on the agent
    victim = next(
        h for h in cluster._nodes
        if cluster.runtime.cluster.nodes() and any(
            rec.get("pid") == h.pid and rec["node_id"] == target.node_id.hex()
            for rec in cluster.runtime.cluster.nodes()
        )
    )
    cluster.remove_node(victim, allow_graceful=False)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(pending, timeout=60)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(actor.slow.remote(), timeout=60)


# --------------------------------------------------------- borrowed refs


def test_nested_ref_borrowed_and_fetched_from_owner(cluster):
    """An ObjectRef NESTED in a task argument (not resolved at dispatch)
    crosses to the agent as a BORROWED reference: the agent pulls the
    value straight from the owner, no object-directory entry needed."""

    big = ray_tpu.put(np.arange(50_000, dtype=np.float64))

    @ray_tpu.remote(num_cpus=1)
    def consume(wrapped):
        import ray_tpu as rt

        ref = wrapped["ref"]  # unpickled inside the agent: borrow path
        arr = rt.get(ref, timeout=30)
        return float(arr.sum())

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]
    out = ray_tpu.get(
        consume.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                remote_nodes[0].node_id
            )
        ).remote({"ref": big}),
        timeout=60,
    )
    assert out == float(np.arange(50_000, dtype=np.float64).sum())


def test_borrow_pins_value_against_owner_gc(cluster):
    """While an agent-held actor keeps a borrowed ref, the owner's last
    handle dying must NOT free the value (the borrow pin); the value is
    reclaimed only after the borrower releases."""
    import gc

    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.held = None

        def hold(self, wrapped):
            self.held = wrapped["ref"]
            return True

        def value_sum(self):
            import ray_tpu as rt

            return float(rt.get(self.held, timeout=30).sum())

        def release(self):
            import gc as _gc

            self.held = None
            _gc.collect()
            return True

    holder = Holder.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(remote_nodes[0].node_id)
    ).remote()
    ref = ray_tpu.put(np.ones(10_000))
    oid = ref.object_id
    store = cluster.runtime.object_store
    assert ray_tpu.get(holder.hold.remote({"ref": ref}), timeout=60) is True
    # wait for the async borrow registration to pin the entry
    deadline = time.monotonic() + 30
    while store.entry(oid).pin_count == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert store.entry(oid).pin_count >= 1, "borrow never registered"

    del ref
    gc.collect()
    # last OWNER handle is gone, but the borrow pin keeps the value
    entry = store.entry(oid)
    assert entry is not None and entry.value is not None
    assert ray_tpu.get(holder.value_sum.remote(), timeout=60) == 10_000.0

    assert ray_tpu.get(holder.release.remote(), timeout=60) is True
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        entry = store.entry(oid)
        if entry is None or entry.value is None:
            break
        time.sleep(0.05)
    assert entry is None or entry.value is None, "unborrow never reclaimed"
