"""Ship-refs-pull-at-executor and arg-locality scheduling (round-4
verdict #4). Reference: dependency_resolver.h:32 inlines only small
args; pull_manager.h:57 pulls large ones at the executing raylet; the
hybrid policy prefers nodes already holding the dependencies.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.object_store import Tier
from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy


@pytest.fixture
def cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def _remote_nodes(cluster):
    return [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]


def _pid_of(cluster, node):
    return next(
        rec["pid"] for rec in cluster.runtime.cluster.nodes()
        if rec["node_id"] == node.node_id.hex()
    )


def test_peer_to_peer_arg_transfer_owner_never_materializes(cluster):
    """A big result living on agent A, passed to a task pinned to agent
    B: B pulls the bytes (necessarily from A — the owner never held
    them), and the owner's entry STAYS a remote placeholder, proving
    the bytes did not route through the owner."""
    nodes = _remote_nodes(cluster)
    a, b = nodes[0], nodes[1]

    @ray_tpu.remote(num_cpus=1)
    def produce():
        return np.arange(2_000_000, dtype=np.float64)  # 16 MB

    @ray_tpu.remote(num_cpus=1)
    def consume(arr):
        return float(arr[1_234_567])

    ref = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(a.node_id)
    ).remote()
    # wait until the result is sealed (REMOTE placeholder at the owner)
    store = cluster.runtime.object_store
    deadline = time.monotonic() + 60
    while not store.is_ready(ref.object_id) and time.monotonic() < deadline:
        time.sleep(0.02)
    entry = store.entry(ref.object_id)
    assert entry.tier == Tier.REMOTE
    assert entry.nbytes == 16_000_000  # producer reported the size

    out = ray_tpu.get(
        consume.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(b.node_id)
        ).remote(ref),
        timeout=120,
    )
    assert out == 1_234_567.0
    # the owner never fetched the value through its own store: the
    # placeholder is untouched (a pull routed through the owner would
    # have materialized it here)
    assert store.entry(ref.object_id).tier == Tier.REMOTE


def test_big_local_arg_ships_as_ref_and_resolves_on_agent(cluster):
    """An owner-held arg above remote_inline_max_bytes ships as a ref;
    the agent pulls it over the chunked plane and the task sees the
    value."""
    nodes = _remote_nodes(cluster)
    big = ray_tpu.put(np.ones(1_500_000, dtype=np.float64))  # 12 MB

    @ray_tpu.remote(num_cpus=1)
    def total(arr):
        return float(arr.sum())

    out = ray_tpu.get(
        total.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(nodes[0].node_id)
        ).remote(big),
        timeout=120,
    )
    assert out == 1_500_000.0


def test_default_strategy_prefers_arg_locality(cluster):
    """With free node choice, a task consuming a big remote-located arg
    lands on the node already holding it."""
    nodes = _remote_nodes(cluster)
    a = nodes[0]
    a_pid = _pid_of(cluster, a)

    @ray_tpu.remote(num_cpus=1)
    def produce():
        return np.arange(2_000_000, dtype=np.float64)

    @ray_tpu.remote(num_cpus=1)
    def consume(arr):
        return os.getpid()

    ref = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(a.node_id)
    ).remote()
    store = cluster.runtime.object_store
    deadline = time.monotonic() + 60
    while not store.is_ready(ref.object_id) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert store.entry(ref.object_id).tier == Tier.REMOTE

    # run several times SEQUENTIALLY (so A always has a free slot):
    # locality must consistently pick A over the equally-idle B/head
    pids = [
        ray_tpu.get(consume.remote(ref), timeout=120) for _ in range(4)
    ]
    assert all(p == a_pid for p in pids), (pids, a_pid)
