"""Cross-node actor restart (round-4 verdict #3): a heartbeat-confirmed
node death re-creates max_restarts>0 actors on a surviving feasible
node — DEAD→RESTARTING→ALIVE with the handle staying valid — while
max_restarts=0 actors die cleanly and in-flight calls fail (the
reference replays nothing either: gcs_actor_manager.h:328).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.exceptions import ActorDiedError
from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy


@pytest.fixture
def cluster():
    c = Cluster(
        head_node_args={
            "num_cpus": 1,
            "_system_config": {"node_stale_s": 2.5, "node_heartbeat_s": 0.2},
        }
    )
    c.add_node(num_cpus=2, resources={"slot": 1},
               system_config={"node_heartbeat_s": 0.2})
    c.add_node(num_cpus=2, resources={"slot": 1},
               system_config={"node_heartbeat_s": 0.2})
    c.wait_for_nodes(3)
    yield c
    c.shutdown()
    from ray_tpu.core.config import cfg

    cfg.reset()


def _agent_handle_for(cluster, node):
    """The NodeHandle of the subprocess backing a RemoteNode."""
    recs = cluster.runtime.cluster.nodes()
    pid = next(
        rec["pid"] for rec in recs if rec["node_id"] == node.node_id.hex()
    )
    return next(h for h in cluster._nodes if h.pid == pid)


def test_actor_restarts_on_surviving_node(cluster):
    """Kill the hosting agent: the next .remote() call succeeds on
    another node, with state rebuilt from __init__, and the named-actor
    directory repoints."""
    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote(num_cpus=1, resources={"slot": 1}, max_restarts=1)
    class Survivor:
        def __init__(self):
            self.calls = 0

        def bump(self):
            self.calls += 1
            return (os.getpid(), self.calls)

    target = remote_nodes[0]
    actor = Survivor.options(
        name="survivor",
        scheduling_strategy=NodeAffinitySchedulingStrategy(target.node_id),
    ).remote()
    pid1, calls = ray_tpu.get(actor.bump.remote(), timeout=60)
    assert calls == 1

    victim = _agent_handle_for(cluster, target)
    cluster.remove_node(victim, allow_graceful=False)

    # the handle keeps working: the call may land during RESTARTING (it
    # queues) or after; either way it executes on the OTHER agent
    deadline = time.monotonic() + 60
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2, calls2 = ray_tpu.get(actor.bump.remote(), timeout=60)
            break
        except ActorDiedError:
            # the death raced the restart transition; retry briefly
            time.sleep(0.2)
    assert pid2 is not None, "actor never came back"
    assert pid2 != pid1
    assert calls2 == 1, "restarted actor must rebuild from __init__"

    # the survivor node hosts it now
    live = {rec["pid"] for rec in cluster.runtime.cluster.nodes()}
    assert pid2 in live

    # named lookup resolves to the restarted incarnation
    again = ray_tpu.get_actor("survivor")
    pid3, _ = ray_tpu.get(again.bump.remote(), timeout=60)
    assert pid3 == pid2


def test_zero_restart_actor_dies_cleanly(cluster):
    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote(num_cpus=1)
    class Mortal:
        def ping(self):
            return os.getpid()

    target = remote_nodes[1]
    actor = Mortal.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(target.node_id)
    ).remote()
    ray_tpu.get(actor.ping.remote(), timeout=60)

    victim = _agent_handle_for(cluster, target)
    cluster.remove_node(victim, allow_graceful=False)

    with pytest.raises(ActorDiedError):
        # retries make no difference: max_restarts defaults to 0
        deadline = time.monotonic() + 60
        while True:
            ray_tpu.get(actor.ping.remote(), timeout=60)
            assert time.monotonic() < deadline
            time.sleep(0.2)


def test_inflight_call_fails_but_handle_survives(cluster):
    """An in-flight call on the dying node fails (no replay), yet the
    restarted actor serves subsequent calls."""
    remote_nodes = [n for n in cluster.runtime.scheduler.nodes() if n.is_remote]

    @ray_tpu.remote(num_cpus=1, resources={"slot": 1}, max_restarts=2)
    class Slow:
        def nap(self, s):
            time.sleep(s)
            return "rested"

        def quick(self):
            return os.getpid()

    target = remote_nodes[0]
    actor = Slow.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(target.node_id)
    ).remote()
    assert ray_tpu.get(actor.quick.remote(), timeout=60) != os.getpid()

    pending = actor.nap.remote(30)
    time.sleep(0.5)  # let it land on the agent
    victim = _agent_handle_for(cluster, target)
    cluster.remove_node(victim, allow_graceful=False)

    with pytest.raises(ActorDiedError):
        ray_tpu.get(pending, timeout=60)

    deadline = time.monotonic() + 60
    pid = None
    while time.monotonic() < deadline:
        try:
            pid = ray_tpu.get(actor.quick.remote(), timeout=60)
            break
        except ActorDiedError:
            time.sleep(0.2)
    assert pid is not None and pid != os.getpid()
