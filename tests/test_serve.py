"""Serve layer: deployments, routing, autoscaling, recovery, LLM engine."""

import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.models import forward, get_config, init_params
from ray_tpu.serve.llm import EngineConfig, LLMEngine, LLMServer, build_llm_app


@pytest.fixture(autouse=True)
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment
class Echo:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix

    def __call__(self, payload):
        return f"{self.prefix}{payload}"

    def shout(self, payload):
        return f"{self.prefix}{payload}".upper()


def test_deploy_and_call():
    handle = serve.run(Echo.bind("pre-"))
    assert ray_tpu.get(handle.remote("x")) == "pre-x"
    assert ray_tpu.get(handle.shout.remote("x")) == "PRE-X"


def test_multiple_replicas_round():
    handle = serve.run(Echo.options(num_replicas=3, name="echo3").bind("r"))
    out = ray_tpu.get([handle.remote(i) for i in range(12)])
    assert out == [f"r{i}" for i in range(12)]
    assert serve.status()["echo3"]["live_replicas"] == 3


def test_get_handle_and_delete():
    serve.run(Echo.bind("a-"), name="named")
    handle = serve.get_handle("named")
    assert ray_tpu.get(handle.remote("z")) == "a-z"
    serve.delete("named")
    with pytest.raises(KeyError):
        serve.get_handle("named")


def test_replica_recovery_after_kill():
    handle = serve.run(Echo.options(name="frag").bind("ok-"))
    controller = serve._get_controller() if hasattr(serve, "_get_controller") else None
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["frag"]
    ray_tpu.kill(state.replicas[0])
    # reconcile loop should replace the dead replica
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            if ray_tpu.get(serve.get_handle("frag").remote("x"), timeout=5) == "ok-x":
                break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("replica not recovered")


def test_http_proxy():
    serve.run(Echo.bind("h-"), name="web")
    port = serve.start_http()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/web",
        data=json.dumps("ping").encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body["result"] == "h-ping"
    # unknown deployment -> 404
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/nope", data=b"{}",
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 404


def test_autoscaling_up():
    @serve.deployment
    class Slow:
        def __call__(self, x):
            time.sleep(0.4)
            return x

    auto = serve.AutoscalingConfig(
        min_replicas=1, max_replicas=3, target_ongoing_requests=1.0, interval_s=0.1
    )
    handle = serve.run(
        Slow.options(name="slow", autoscaling=auto, num_replicas=1).bind()
    )
    refs = [handle.remote(i) for i in range(8)]
    deadline = time.time() + 15
    peaked = 1
    while time.time() < deadline:
        peaked = max(peaked, serve.status()["slow"]["live_replicas"])
        if peaked >= 2:
            break
        time.sleep(0.1)
    ray_tpu.get(refs, timeout=60)
    assert peaked >= 2, f"never scaled up: {serve.status()}"


# ------------------------------------------------------------------ LLM engine


def _greedy_reference(config, params, prompt, n):
    """Greedy decode via repeated full forward — ground truth."""
    tokens = list(prompt)
    for _ in range(n):
        logits = forward(params, np.asarray([tokens], dtype=np.int32), config)
        tokens.append(int(np.argmax(np.asarray(logits[0, -1]))))
    return tokens[len(prompt):]


def test_engine_greedy_matches_full_forward():
    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = LLMEngine(config, params, EngineConfig(max_slots=4))
    try:
        prompt = [5, 17, 42, 7]
        got = engine.generate(prompt, max_tokens=8)
        expected = _greedy_reference(config, params, prompt, 8)
        assert got == expected, (got, expected)
    finally:
        engine.shutdown()


def test_engine_continuous_batching_staggered():
    """Requests arriving mid-flight batch with ongoing ones and all finish
    correctly (the continuous-batching property)."""
    config = get_config("gpt2-tiny")
    params = init_params(config, jax.random.PRNGKey(1))
    engine = LLMEngine(config, params, EngineConfig(max_slots=4))
    try:
        prompts = [[1, 2, 3], [9, 8], [30, 31, 32, 33], [4], [100, 101]]
        streams = []
        for i, p in enumerate(prompts):
            streams.append((p, engine.submit(p, max_tokens=6)))
            time.sleep(0.02)  # staggered arrivals
        for p, s in streams:
            got = s.result(timeout=60)
            expected = _greedy_reference(config, params, p, 6)
            assert got == expected, (p, got, expected)
        assert engine.metrics["prefills"] == 5
    finally:
        engine.shutdown()


def test_engine_more_requests_than_slots():
    config = get_config("gpt2-tiny")
    params = init_params(config, jax.random.PRNGKey(1))
    engine = LLMEngine(config, params, EngineConfig(max_slots=2))
    try:
        streams = [engine.submit([i + 1, i + 2], max_tokens=4) for i in range(6)]
        results = [s.result(timeout=120) for s in streams]
        for i, got in enumerate(results):
            expected = _greedy_reference(config, params, [i + 1, i + 2], 4)
            assert got == expected
    finally:
        engine.shutdown()


def test_engine_ttft_and_metrics():
    config = get_config("gpt2-tiny")
    params = init_params(config, jax.random.PRNGKey(1))
    engine = LLMEngine(config, params, EngineConfig(max_slots=2))
    try:
        s = engine.submit([1, 2, 3], max_tokens=5)
        s.result(timeout=60)
        assert s.ttft_s is not None and s.ttft_s > 0
        assert engine.metrics["generated_tokens"] == 5
    finally:
        engine.shutdown()


def test_llm_server_deployment_end_to_end():
    app = build_llm_app("gpt2-tiny", name="llm", max_slots=2)
    handle = serve.run(app)
    out = ray_tpu.get(
        handle.generate.remote({"prompt_tokens": [1, 2, 3], "max_tokens": 4}),
        timeout=120,
    )
    assert len(out["tokens"]) == 4
    assert out["usage"]["total_tokens"] == 7
    metrics = ray_tpu.get(handle.metrics.remote({}), timeout=30)
    assert metrics["generated_tokens"] >= 4
