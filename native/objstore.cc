// Shared-memory arena object store — the plasma-equivalent mechanism layer.
//
// Reference parity: src/ray/object_manager/plasma/ (PlasmaStore store.h:55,
// dlmalloc arena plasma/dlmalloc.cc, LRU EvictionPolicy eviction_policy.h:159).
// This is the TPU-host rebuild of that component: one contiguous arena,
// first-fit free-list allocation with coalescing, pin counts, and an LRU
// list of evictable (sealed, unpinned) objects. Policy split: this library
// owns placement + LRU ordering; the Python runtime drives spilling
// (asks for the LRU candidate, persists it, then deletes) so storage
// backends stay pluggable.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <list>
#include <map>
#include <mutex>
#include <sys/mman.h>
#include <unistd.h>
#include <unordered_map>

namespace {

struct Object {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  uint32_t pins = 0;
  bool in_lru = false;
  std::list<uint64_t>::iterator lru_it{};
};

struct FreeBlock {
  uint64_t size;
};

struct Arena {
  char* base = nullptr;
  bool mapped = false;  // base is an mmap of backing_fd (shared arena)
  int backing_fd = -1;
  uint64_t capacity = 0;
  uint64_t used = 0;
  // offset -> free block size, ordered for coalescing
  std::map<uint64_t, uint64_t> free_blocks;
  std::unordered_map<uint64_t, Object> objects;
  std::list<uint64_t> lru;  // front = oldest evictable
  std::mutex mu;
};

constexpr uint64_t kAlign = 64;  // cacheline alignment for numpy payloads

uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

void lru_remove(Arena* a, Object& obj) {
  if (obj.in_lru) {
    a->lru.erase(obj.lru_it);
    obj.in_lru = false;
  }
}

void lru_push(Arena* a, uint64_t id, Object& obj) {
  if (!obj.in_lru && obj.sealed && obj.pins == 0) {
    a->lru.push_back(id);
    obj.lru_it = std::prev(a->lru.end());
    obj.in_lru = true;
  }
}

// merge [offset,size) into the free map, coalescing neighbors
void free_insert(Arena* a, uint64_t offset, uint64_t size) {
  auto next = a->free_blocks.lower_bound(offset);
  if (next != a->free_blocks.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == offset) {
      offset = prev->first;
      size += prev->second;
      a->free_blocks.erase(prev);
    }
  }
  if (next != a->free_blocks.end() && offset + size == next->first) {
    size += next->second;
    a->free_blocks.erase(next);
  }
  a->free_blocks[offset] = size;
}

}  // namespace

extern "C" {

void* store_create_arena(uint64_t capacity) {
  auto* a = new Arena();
  a->base = static_cast<char*>(std::malloc(capacity));
  if (a->base == nullptr) {
    delete a;
    return nullptr;
  }
  a->capacity = capacity;
  a->free_blocks[0] = capacity;
  return a;
}

// Cross-process arena: the payload pages live in a file (put it under
// /dev/shm) mapped MAP_SHARED, so worker processes can mmap the same
// file and read sealed objects ZERO-COPY by (offset, size) descriptor —
// the reference's plasma client protocol (plasma/store.h:55,
// client.cc mmap of the store's fd), minus the socket: descriptors ride
// the existing worker pipes, and allocation stays owner-side.
void* store_create_arena_shared(uint64_t capacity, const char* path) {
  int fd = ::open(path, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    ::close(fd);
    ::unlink(path);  // never leave a zero/partial tmpfs file behind
    return nullptr;
  }
  void* base = ::mmap(nullptr, capacity, PROT_READ | PROT_WRITE,
                      MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    ::unlink(path);
    return nullptr;
  }
  auto* a = new Arena();
  a->base = static_cast<char*>(base);
  a->mapped = true;
  a->backing_fd = fd;
  a->capacity = capacity;
  a->free_blocks[0] = capacity;
  return a;
}

void store_destroy_arena(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  if (a == nullptr) return;
  if (a->mapped) {
    ::munmap(a->base, a->capacity);
    if (a->backing_fd >= 0) ::close(a->backing_fd);
  } else {
    std::free(a->base);
  }
  delete a;
}

// Returns the offset of the new (unsealed) object, or -1 if no space /
// duplicate id. The caller is expected to memcpy into base+offset and seal.
int64_t store_create(void* handle, uint64_t id, uint64_t size) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  if (a->objects.count(id)) return -1;
  uint64_t need = align_up(size == 0 ? 1 : size);
  // first fit
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      uint64_t offset = it->first;
      uint64_t remaining = it->second - need;
      a->free_blocks.erase(it);
      if (remaining > 0) a->free_blocks[offset + need] = remaining;
      Object obj;
      obj.offset = offset;
      obj.size = size;
      a->objects.emplace(id, obj);
      a->used += need;
      return static_cast<int64_t>(offset);
    }
  }
  return -1;
}

// Seal does NOT enter the object into the LRU: a freshly sealed object is
// readable but not yet evictable, so callers can finish their own
// bookkeeping race-free and then flip it evictable explicitly.
int store_seal(void* handle, uint64_t id) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->objects.find(id);
  if (it == a->objects.end() || it->second.sealed) return -1;
  it->second.sealed = true;
  return 0;
}

// Enter a sealed, unpinned object into the LRU (eviction eligibility).
int store_make_evictable(void* handle, uint64_t id) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->objects.find(id);
  if (it == a->objects.end() || !it->second.sealed) return -1;
  lru_push(a, id, it->second);
  return 0;
}

// Bumped whenever an exported signature or behavior changes; the Python
// binding refuses to drive a stale prebuilt .so (it rebuilds instead).
uint64_t store_abi_version(void* /*unused*/) { return 3; }

// Pins the object and returns its offset (-1 if absent/unsealed). Pinned
// objects are never eviction candidates.
int64_t store_get(void* handle, uint64_t id, uint64_t* size_out) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->objects.find(id);
  if (it == a->objects.end() || !it->second.sealed) return -1;
  Object& obj = it->second;
  lru_remove(a, obj);
  obj.pins += 1;
  if (size_out != nullptr) *size_out = obj.size;
  return static_cast<int64_t>(obj.offset);
}

int store_unpin(void* handle, uint64_t id) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->objects.find(id);
  if (it == a->objects.end() || it->second.pins == 0) return -1;
  it->second.pins -= 1;
  lru_push(a, id, it->second);  // re-enters LRU at the fresh end
  return 0;
}

int store_delete(void* handle, uint64_t id) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->objects.find(id);
  if (it == a->objects.end() || it->second.pins > 0) return -1;
  Object& obj = it->second;
  lru_remove(a, obj);
  uint64_t need = align_up(obj.size == 0 ? 1 : obj.size);
  free_insert(a, obj.offset, need);
  a->used -= need;
  a->objects.erase(it);
  return 0;
}

// Oldest sealed+unpinned object — the eviction/spill candidate. Writes the
// id to id_out and returns 0, or -1 if none. (Out-param, not a returned
// int64: ids are full-range uint64 hashes, so the top bit is routinely set
// and an in-band -1 sentinel would misread half of all ids as "none".)
int store_lru_candidate(void* handle, uint64_t* id_out) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  if (a->lru.empty()) return -1;
  *id_out = a->lru.front();
  return 0;
}

uint64_t store_used(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->used;
}

uint64_t store_capacity(void* handle) {
  return static_cast<Arena*>(handle)->capacity;
}

uint64_t store_num_objects(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->objects.size();
}

uint64_t store_num_free_blocks(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->free_blocks.size();
}

void* store_base(void* handle) {
  return static_cast<Arena*>(handle)->base;
}

}  // extern "C"
