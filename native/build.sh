#!/bin/sh
# Build the native components into ray_tpu/core/_native/.
#
# Build to a temp file and rename: the rename gives the .so a fresh inode,
# so a process that already dlopen'ed a stale copy (e.g. the ABI probe in
# native_store._load_lib) keeps its old mapping intact and a subsequent
# dlopen of the path maps the NEW file — relinking in place would rewrite
# pages under a live mapping (undefined behavior) and dlopen would dedup
# to the stale handle.
set -e
cd "$(dirname "$0")"
mkdir -p ../ray_tpu/core/_native
out=../ray_tpu/core/_native/libobjstore.so
g++ -O2 -shared -fPIC -std=c++17 -Wall -o "$out.tmp.$$" objstore.cc
mv -f "$out.tmp.$$" "$out"
echo "built ray_tpu/core/_native/libobjstore.so"
