#!/bin/sh
# Build the native components into ray_tpu/core/_native/.
set -e
cd "$(dirname "$0")"
mkdir -p ../ray_tpu/core/_native
g++ -O2 -shared -fPIC -std=c++17 -Wall -o ../ray_tpu/core/_native/libobjstore.so objstore.cc
echo "built ray_tpu/core/_native/libobjstore.so"
