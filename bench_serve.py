"""Serve benchmark: continuous-batched LLM inference req/s + p50 TTFT.

The BASELINE.md north-star for serving ("req/s and p50 TTFT for
continuous-batched LLM inference on TPU"). Workload: a closed burst of
GPT-2-124M requests (192-token prompts, 48 generated tokens each) against
the paged continuous-batching engine (paged KV pool + chunked prefill,
ray_tpu/serve/llm/paged_engine.py).

Prints ONE JSON line. vs_baseline is target_p50_ttft / measured_p50_ttft
with a 0.5 s target under full 8-way slot contention — TTFT is the
latency metric continuous batching exists to protect, and 0.5 s is
interactive-serving territory for a burst 4x deeper than the slot count.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import numpy as np

N_REQUESTS = 32
PROMPT_LEN = 192
MAX_TOKENS = 48
TTFT_TARGET_S = 0.5


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard the engine over a "
                         "tp mesh of this many devices (1 = single device)")
    ap.add_argument("--model", default="gpt2-small")
    ap.add_argument("--openai", action="store_true",
                    help="drive the workload through the OpenAI-compatible "
                         "HTTP endpoint (/v1/completions) instead of the "
                         "engine API")
    args = ap.parse_args()
    if args.openai:
        bench_openai(args)
        return

    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm.paged import PagedConfig
    from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine

    config = get_config(args.model)
    mesh = None
    if args.tp > 1:
        from ray_tpu.parallel import MeshSpec, build_mesh

        mesh = build_mesh(
            MeshSpec(tp=args.tp), devices=jax.devices()[: args.tp]
        )
    params = init_params(config, jax.random.PRNGKey(0))
    engine = PagedLLMEngine(
        config,
        params,
        PagedEngineConfig(
            max_slots=8,
            decode_block_steps=24,
            precompile=True,  # no XLA compile ever lands inside a request
            paged=PagedConfig(
                page_size=64, num_pages=512, max_pages_per_slot=8, chunk_pages=4
            ),
        ),
        mesh=mesh,
    )
    rng = np.random.default_rng(0)

    def prompt():
        return [int(t) for t in rng.integers(1, config.vocab_size, size=PROMPT_LEN)]

    try:
        # warmup: trigger every compile (chunk prefill, decode, sample)
        engine.generate(prompt(), max_tokens=4)

        streams = []
        t0 = time.perf_counter()
        for _ in range(N_REQUESTS):
            streams.append(engine.submit(prompt(), max_tokens=MAX_TOKENS))
        outs = [s.result(timeout=600) for s in streams]
        elapsed = time.perf_counter() - t0

        assert all(len(o) == MAX_TOKENS for o in outs), "short generation"
        ttfts = sorted(s.ttft_s for s in streams)
        p50 = ttfts[len(ttfts) // 2]
        p95 = ttfts[int(len(ttfts) * 0.95)]
        # first wave = the 8 requests admitted immediately: their TTFT is
        # pure prefill+first-block latency, no queue wait — the number
        # batched prefill actually moves
        first_wave = sorted(s.ttft_s for s in streams[:8])
        p50_first = first_wave[len(first_wave) // 2]
        decode_tps = N_REQUESTS * MAX_TOKENS / elapsed
        print(
            json.dumps(
                {
                    "metric": "gpt2_124m_serve_req_per_s",
                    "value": round(N_REQUESTS / elapsed, 2),
                    "unit": "req/s",
                    "vs_baseline": round(TTFT_TARGET_S / p50, 3),
                    "p50_ttft_s": round(p50, 4),
                    "p95_ttft_s": round(p95, 4),
                    "p50_ttft_first_wave_s": round(p50_first, 4),
                    "decode_tokens_per_s": round(decode_tps, 1),
                    "device_kind": getattr(
                        jax.devices()[0], "device_kind", "unknown"
                    ),
                    "tp": args.tp,
                }
            )
        )
    finally:
        engine.shutdown()


def bench_openai(args) -> None:
    """Same burst, driven through the OpenAI HTTP surface: measures the
    full ingress path (HTTP + schema translation + serve routing +
    engine). TTFT is not observable per-request without SSE timing, so
    this reports req/s and decode tok/s through the endpoint."""
    import threading
    import urllib.request

    import ray_tpu
    from ray_tpu import serve as serve_mod
    from ray_tpu.serve.llm import serve_openai

    ray_tpu.init(detect_accelerators=True)
    frontend = serve_openai(
        model=args.model, paged=True, max_slots=8, tensor_parallel=args.tp
    )
    url = f"http://127.0.0.1:{frontend.port}/v1/completions"
    from ray_tpu.models import get_config as _get_config

    rng = np.random.default_rng(0)
    vocab = _get_config(args.model).vocab_size

    def post(i, results):
        prompt = [int(t) for t in rng.integers(1, vocab, size=PROMPT_LEN)]
        req = urllib.request.Request(
            url,
            data=json.dumps({
                "model": args.model, "prompt": prompt,
                "max_tokens": MAX_TOKENS, "temperature": 0.0,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=600) as r:
            results[i] = json.loads(r.read())

    try:
        results: dict = {}
        post(-1, results)  # warmup compiles
        threads = []
        t0 = time.perf_counter()
        for i in range(N_REQUESTS):
            t = threading.Thread(target=post, args=(i, results))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=600)
        elapsed = time.perf_counter() - t0
        done = [results[i] for i in range(N_REQUESTS) if i in results]
        assert len(done) == N_REQUESTS, f"only {len(done)} completed"
        assert all(
            r["usage"]["completion_tokens"] == MAX_TOKENS for r in done
        )
        print(json.dumps({
            "metric": "gpt2_124m_openai_http_req_per_s",
            "value": round(N_REQUESTS / elapsed, 2),
            "unit": "req/s",
            "vs_baseline": 0.0,
            "decode_tokens_per_s": round(N_REQUESTS * MAX_TOKENS / elapsed, 1),
            "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
            "tp": args.tp,
        }))
    finally:
        frontend.stop()
        serve_mod.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
