"""Open-loop serve benchmark: streaming throughput under Poisson load.

The BASELINE.md serve north-star, upgraded from a closed burst to an
OPEN-LOOP harness: requests arrive on a Poisson clock whether or not the
engine has kept up (closed loops hide queueing collapse — a slow server
sees a slow client), every request streams, and the prompt mix models a
production chat fleet: a configurable fraction of requests share one of
a few long system prompts (the prefix-cache workload), the rest are
unique.

Two phases run on identical workloads — prefix cache OFF (baseline) then
ON — and ONE JSON line reports both: p50/p99 TTFT, p50 TPOT, tokens/s
per chip, and the prefix-cache hit rate. vs_baseline is the tokens/s
ratio ON/OFF: what page-level KV reuse buys at this shared-prefix mix.

Optional chaos: --chaos runs the same open-loop workload through a
2-replica serve deployment and kills one replica actor mid-run — the
controller restarts it and the router fails requests over, so the drill
passes when every request still completes.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time

import jax
import numpy as np

TTFT_TARGET_S = 0.5

# Workload/engine defaults per backend. The CPU profile (smoke runs,
# CI) stretches the tiny model's rope table to 512 so a
# production-length shared system prompt fits, and uses single-page
# prefill chunks so a prompt spans many chunk launches — prefill cost
# then scales with the tokens actually computed (as it does on TPU,
# where FLOPs track real tokens) instead of being one fixed-shape
# launch that hides what the prefix cache skipped.
_PROFILES = {
    "tpu": dict(model="gpt2-small", requests=192, rate=24.0,
                prompt_len=192, max_tokens=48, system_len=128,
                page_size=64, chunk_pages=4, decode_block_steps=24,
                pages=512, max_seq=0, slots=8),
    "cpu": dict(model="llama-tiny", requests=64, rate=500.0,
                prompt_len=368, max_tokens=4, system_len=352,
                page_size=16, chunk_pages=1, decode_block_steps=2,
                pages=768, max_seq=512, slots=16),
}

# The speculative drill is decode-bound (speculation only pays during
# decode), so it flips the workload shape: short prompts, long
# generations, prefix cache off in every phase.
_SPEC_PROFILES = {
    "tpu": dict(model="gpt2-small", requests=64, rate=24.0,
                prompt_len=64, max_tokens=48, system_len=32,
                page_size=64, chunk_pages=2, decode_block_steps=8,
                pages=512, max_seq=0, slots=8),
    "cpu": dict(model="llama-tiny", requests=32, rate=200.0,
                prompt_len=48, max_tokens=24, system_len=32,
                page_size=16, chunk_pages=1, decode_block_steps=2,
                pages=256, max_seq=0, slots=8),
}


def _emit_result(payload: dict, rc: int = 0) -> None:
    """Print the ONE result line and self-capture it as the next
    BENCH_SERVE_r<NN>.json round file (same {n, cmd, rc, tail, parsed}
    shape the driver writes for bench.py), anchored to the repo root so
    the round history survives whatever cwd the bench ran from."""
    line = json.dumps(payload)
    print(line)
    root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(os.path.basename(p)[len("BENCH_SERVE_r"):-len(".json")])
        for p in glob.glob(os.path.join(root, "BENCH_SERVE_r*.json"))
        if os.path.basename(p)[len("BENCH_SERVE_r"):-len(".json")].isdigit()
    ]
    n = max(rounds, default=0) + 1
    path = os.path.join(root, f"BENCH_SERVE_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump(
            {
                "n": n,
                "cmd": "python " + " ".join(sys.argv),
                "rc": rc,
                "tail": line + "\n",
                "parsed": payload,
            },
            f,
        )
        f.write("\n")


def _resolve_profile(args) -> None:
    table = _SPEC_PROFILES if args.speculative else _PROFILES
    prof = table["tpu" if jax.default_backend() == "tpu" else "cpu"]
    for key, value in prof.items():
        if getattr(args, key) is None:
            setattr(args, key, value)


def _clamp_to_model(args) -> None:
    """--chaos/--openai deploy engines that keep the model's own
    max_seq (no --max-seq override reaches them), so shrink the
    workload to fit when the profile's prompts would overflow."""
    from ray_tpu.models import get_config

    cap = get_config(args.model).max_seq
    if args.prompt_len + args.max_tokens > cap:
        args.prompt_len = cap - args.max_tokens
        args.system_len = min(args.system_len, args.prompt_len // 2)


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, int(len(xs) * q))]


def _build_workload(args, vocab: int):
    """Deterministic request list: (arrival_offset_s, prompt). A
    shared_frac slice reuses one of n_system long system prompts with a
    unique tail; the rest are fully unique prompts of the same length."""
    rng = np.random.default_rng(0)
    gaps = rng.exponential(1.0 / args.rate, size=args.requests)
    arrivals = np.cumsum(gaps)
    systems = [
        [int(t) for t in rng.integers(1, vocab, size=args.system_len)]
        for _ in range(args.n_system)
    ]
    tail_len = args.prompt_len - args.system_len
    requests = []
    for i in range(args.requests):
        if rng.random() < args.shared_frac:
            system = systems[int(rng.integers(len(systems)))]
            prompt = list(system) + [
                int(t) for t in rng.integers(1, vocab, size=tail_len)
            ]
        else:
            prompt = [int(t) for t in rng.integers(1, vocab, size=args.prompt_len)]
        requests.append((float(arrivals[i]), prompt))
    return requests, systems


def _drain(stream, rec):
    """Collector: stream tokens, recording first/last token wall time and
    the token ids themselves (the speculative drill replays them as
    drafts and cross-checks spec-on output exactness)."""
    n = 0
    toks = rec["toks"] = []
    try:
        for tok in stream:
            now = time.perf_counter()
            if n == 0:
                rec["first"] = now
            rec["last"] = now
            toks.append(tok)
            n += 1
    except Exception as exc:  # noqa: BLE001 - report, don't kill the bench
        rec["error"] = repr(exc)
    rec["tokens"] = n
    rec["ttft_engine"] = stream.ttft_s


def _run_open_loop(args, config, params, mesh, prefix_cache: bool,
                   spec_tokens: int = 0, proposer=None):
    from ray_tpu.serve.llm.paged import PagedConfig
    from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine

    engine = PagedLLMEngine(
        config, params,
        PagedEngineConfig(
            max_slots=args.slots,
            decode_block_steps=args.decode_block_steps,
            speculative_tokens=spec_tokens,
            speculative_proposer=proposer,
            precompile=True,  # no XLA compile ever lands inside a request
            paged=PagedConfig(
                page_size=args.page_size, num_pages=args.pages,
                max_pages_per_slot=max(
                    8, -(-(args.prompt_len + args.max_tokens) // args.page_size)
                ),
                chunk_pages=args.chunk_pages, prefix_cache=prefix_cache,
            ),
        ),
        mesh=mesh,
    )
    requests, systems = _build_workload(args, config.vocab_size)
    try:
        # Warm outside the timed window: compile/launch paths AND (when
        # the cache is on) the shared system prompts — a production cache
        # is measured warm; cold-start misses are a separate axis.
        engine.generate(requests[0][1][: args.prompt_len], max_tokens=4)
        for system in systems:
            engine.generate(system, max_tokens=1)
        recs = [dict() for _ in requests]
        threads = []
        t0 = time.perf_counter()
        for (offset, prompt), rec in zip(requests, recs):
            now = time.perf_counter() - t0
            if offset > now:
                time.sleep(offset - now)
            rec["submitted"] = time.perf_counter()
            stream = engine.submit(prompt, max_tokens=args.max_tokens)
            t = threading.Thread(target=_drain, args=(stream, rec), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=900)
        elapsed = time.perf_counter() - t0
        stats = engine.stats()
    finally:
        engine.shutdown()

    errors = [r for r in recs if "error" in r]
    assert not errors, f"{len(errors)} request(s) failed: {errors[0]['error']}"
    total_tokens = sum(r["tokens"] for r in recs)
    assert total_tokens == args.requests * args.max_tokens, "short generation"
    ttfts = [r["ttft_engine"] for r in recs if r["ttft_engine"] is not None]
    tpots = [
        (r["last"] - r["first"]) / (r["tokens"] - 1)
        for r in recs if r["tokens"] > 1
    ]
    return {
        "tokens_per_s": total_tokens / elapsed,
        "p50_ttft_s": _percentile(ttfts, 0.50),
        "p99_ttft_s": _percentile(ttfts, 0.99),
        "p50_tpot_s": _percentile(tpots, 0.50),
        "prefix_hit_rate": stats.get("prefix_cache_hit_rate", 0.0),
        "prefix_cache_pages": stats.get("prefix_cache_pages", 0.0),
        "mixed_ticks": stats.get("mixed_ticks", 0.0),
        "decode_steps": stats.get("decode_steps", 0.0),
        "decode_tokens": stats.get("decode_tokens", 0.0),
        "spec_proposed": stats.get("spec_proposed", 0.0),
        "spec_acceptance_rate": stats.get("spec_acceptance_rate", 0.0),
        "spec_rollback_pages": stats.get("spec_rollback_pages", 0.0),
        "outputs": [r["toks"] for r in recs],
        "elapsed_s": elapsed,
    }


class _RingProposer:
    """Adversarial drill proposer: drafts a +1 token ring the greedy
    chain almost never follows, pinning acceptance near zero so every
    verify round pays rejection + rollback (the speculation-can't-stall
    worst case)."""

    def __init__(self, vocab: int):
        self.vocab = vocab

    def propose(self, context, k):
        return [(context[-1] + 1 + i) % self.vocab for i in range(k)]


def bench_speculative(args, config, params, mesh) -> None:
    """Three phases on the IDENTICAL open-loop workload, prefix cache
    off throughout (speculation is the only variable):

    1. spec OFF — records every request's greedy output;
    2. spec ON, replay drill — a ReplayProposer drafts from phase 1's
       recorded outputs, pinning acceptance ~1 (the templated/agentic
       upper bound) and shrinking verify launches per generated token;
    3. spec ON, adversarial drill — always-wrong drafts, acceptance ~0:
       output must STILL be exact and decode must not stall.

    Both spec phases are cross-checked token-for-token against phase 1
    (exactness is part of the bench, not just the test suite)."""
    from ray_tpu.serve.llm.speculative import ReplayProposer

    base = _run_open_loop(args, config, params, mesh, prefix_cache=False)
    requests, _ = _build_workload(args, config.vocab_size)
    replay = ReplayProposer({
        tuple(prompt): toks
        for (_, prompt), toks in zip(requests, base["outputs"])
    })
    spec = _run_open_loop(
        args, config, params, mesh, prefix_cache=False,
        spec_tokens=args.spec_tokens, proposer=replay,
    )
    adv = _run_open_loop(
        args, config, params, mesh, prefix_cache=False,
        spec_tokens=args.spec_tokens,
        proposer=_RingProposer(config.vocab_size),
    )
    assert spec["outputs"] == base["outputs"], "replay drill diverged"
    assert adv["outputs"] == base["outputs"], "adversarial drill diverged"

    def launches_per_token(run):
        return run["decode_steps"] / max(1.0, run["decode_tokens"])

    launch_reduction = launches_per_token(base) / max(
        1e-9, launches_per_token(spec)
    )
    assert spec["spec_acceptance_rate"] >= 0.6, spec["spec_acceptance_rate"]
    assert launch_reduction >= 1.8, launch_reduction
    n_chips = max(1, args.tp)
    _emit_result({
        "metric": "serve_speculative_tokens_per_s_per_chip",
        "value": round(spec["tokens_per_s"] / n_chips, 1),
        "unit": "tok/s/chip",
        # speculation speedup at replay (high-acceptance) drafts
        "vs_baseline": round(
            spec["tokens_per_s"] / max(1e-9, base["tokens_per_s"]), 3
        ),
        "spec_tokens": args.spec_tokens,
        "acceptance_rate": round(spec["spec_acceptance_rate"], 3),
        "launches_per_token": round(launches_per_token(spec), 4),
        "baseline_launches_per_token": round(launches_per_token(base), 4),
        "launch_reduction": round(launch_reduction, 3),
        "p50_tpot_s": round(spec["p50_tpot_s"], 5),
        "baseline_p50_tpot_s": round(base["p50_tpot_s"], 5),
        "adversarial_acceptance_rate": round(adv["spec_acceptance_rate"], 3),
        "adversarial_p50_tpot_s": round(adv["p50_tpot_s"], 5),
        "adversarial_rollback_pages": adv["spec_rollback_pages"],
        "outputs_exact": True,
        "requests": args.requests,
        "arrival_rate_req_s": args.rate,
        "prompt_len": args.prompt_len,
        "max_tokens": args.max_tokens,
        "page_size": args.page_size,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "tp": args.tp,
    })


def bench_forensics(args, config, params, mesh) -> None:
    """Request-recorder overhead A/B: the IDENTICAL open-loop workload
    with the forensics recorder OFF (baseline) then ON. The recorder is
    a deque append under a lock per phase mark — the acceptance bar is
    tokens/s with the recorder on within 2% of off."""
    from ray_tpu.core.config import cfg
    from ray_tpu.serve import reqlog

    cfg.set(serve_request_log=False)
    try:
        off = _run_open_loop(args, config, params, mesh, prefix_cache=True)
    finally:
        cfg.reset()
    reqlog.log().clear()
    cfg.set(serve_request_log=True)
    try:
        on = _run_open_loop(args, config, params, mesh, prefix_cache=True)
        recorder = reqlog.log().stats()
    finally:
        cfg.reset()
    ratio = on["tokens_per_s"] / max(1e-9, off["tokens_per_s"])
    _emit_result({
        "metric": "serve_forensics_recorder_tokens_per_s_ratio",
        "value": round(ratio, 4),
        "unit": "fraction",
        # overhead budget: recorder-on throughput within 2% of off
        "vs_baseline": round(ratio, 4),
        "within_2pct": ratio >= 0.98,
        "tokens_per_s_recorder_on": round(on["tokens_per_s"], 1),
        "tokens_per_s_recorder_off": round(off["tokens_per_s"], 1),
        "p99_ttft_s_recorder_on": round(on["p99_ttft_s"], 4),
        "p99_ttft_s_recorder_off": round(off["p99_ttft_s"], 4),
        "marks_recorded": recorder["seq"],
        "requests_indexed": recorder["indexed_requests"],
        "requests": args.requests,
        "arrival_rate_req_s": args.rate,
        "prompt_len": args.prompt_len,
        "max_tokens": args.max_tokens,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "tp": args.tp,
    })


def _preemption_drill(config, params) -> dict:
    """Lane-preemption acceptance sub-drill: one slot, a low-priority
    long decode, then a high-priority arrival. The victim must be
    parked (trimmed to its emitted frontier), the preemptor served, and
    the victim resumed TOKEN-EXACT — with every page refcount restored
    once both streams drain (prefix-shared pages survive untouched)."""
    from ray_tpu.serve.llm.paged import PagedConfig
    from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine

    num_pages = 64
    engine = PagedLLMEngine(
        config, params,
        PagedEngineConfig(
            max_slots=1, decode_block_steps=2, precompile=True,
            paged=PagedConfig(page_size=8, num_pages=num_pages,
                              max_pages_per_slot=8, chunk_pages=2),
        ),
    )
    try:
        rng = np.random.default_rng(7)
        victim_prompt = [int(t) for t in
                         rng.integers(1, config.vocab_size, size=16)]
        high_prompt = [int(t) for t in
                       rng.integers(1, config.vocab_size, size=16)]
        # greedy reference on the same engine — also warms the prefix
        # cache so the victim's first pages are SHARED (the park must
        # only drop refcounts on them, never corrupt the cached KV)
        reference = engine.generate(victim_prompt, max_tokens=24)
        victim = engine.submit(victim_prompt, max_tokens=24,
                               tenant="free", priority=0)
        victim_iter = iter(victim)
        first = next(victim_iter)  # victim is decoding before the preemptor
        high = engine.submit(high_prompt, max_tokens=6,
                             tenant="paid", priority=1)
        high_tokens = high.result(timeout=300)
        victim_tokens = [first] + list(victim_iter)
        # cache hit over the shared prefix must still reproduce reference
        replay = engine.generate(victim_prompt, max_tokens=24)
        deadline = time.perf_counter() + 30
        restored = False
        while time.perf_counter() < deadline and not restored:
            stats = engine.stats()
            restored = (stats["pages_free"] + stats["prefix_cache_pages"]
                        == num_pages - 1)
            if not restored:
                time.sleep(0.05)
        stats = engine.stats()
        assert stats["lane_preemptions"] >= 1, "drill never preempted"
        assert victim_tokens == reference, "victim resume not token-exact"
        assert replay == reference, "prefix-shared pages corrupted"
        assert len(high_tokens) == 6, "preemptor starved"
        assert restored, f"page refcounts not restored: {stats}"
        return {
            "lane_preemptions": stats["lane_preemptions"],
            "lane_resumes": stats["lane_resumes"],
            "preempted_pages": stats["preempted_pages"],
            "token_exact_resume": True,
            "pages_restored": True,
        }
    finally:
        engine.shutdown()


def bench_multitenant(args) -> None:
    """Adversarial multi-tenant overload drill on ONE paged engine: a
    flooding low-priority 'free' tenant (token-bucket quota, weight 1)
    against a paying 'paid' tenant (weight 4, priority 1, TTFT SLO) on
    a merged Poisson mix. Passes when the paying tenant's TTFT SLO
    attainment stays >= 0.95 while the flood is shed with TYPED
    BackPressureError 429s carrying honest Retry-After estimates — and
    the lane-preemption sub-drill resumes token-exact."""
    import dataclasses

    from ray_tpu.core.exceptions import BackPressureError
    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve import tenancy
    from ray_tpu.serve.llm.paged import PagedConfig
    from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine

    config = get_config(args.model)
    if args.max_seq:
        config = dataclasses.replace(config, max_seq=args.max_seq)
    params = init_params(config, jax.random.PRNGKey(0))

    on_tpu = jax.default_backend() == "tpu"
    ttft_slo_s = TTFT_TARGET_S if on_tpu else 2.5
    paid_n, paid_rate = 24, 30.0
    free_n, free_rate = 96, 120.0
    prompt_len, max_tokens = 64, 8
    tenancy.reset()
    tenancy.set_tenant("paid", weight=4.0, priority=1,
                       ttft_slo_s=ttft_slo_s)
    tenancy.set_tenant("free", weight=1.0, priority=0,
                       quota_rps=30.0, quota_burst=12.0)

    engine = PagedLLMEngine(
        config, params,
        PagedEngineConfig(
            max_slots=8, decode_block_steps=args.decode_block_steps,
            precompile=True,
            paged=PagedConfig(
                page_size=16, num_pages=192,
                max_pages_per_slot=max(
                    8, -(-(prompt_len + max_tokens) // 16)
                ),
                chunk_pages=args.chunk_pages,
            ),
        ),
    )
    rng = np.random.default_rng(0)
    arrivals = sorted(
        [(float(t), "paid") for t in np.cumsum(
            rng.exponential(1.0 / paid_rate, size=paid_n))]
        + [(float(t), "free") for t in np.cumsum(
            rng.exponential(1.0 / free_rate, size=free_n))]
    )
    try:
        engine.generate([1] * prompt_len, max_tokens=2)  # warm compile
        recs, threads = [], []
        sheds = []
        t0 = time.perf_counter()
        for offset, tenant in arrivals:
            now = time.perf_counter() - t0
            if offset > now:
                time.sleep(offset - now)
            prompt = [int(t) for t in
                      rng.integers(1, config.vocab_size, size=prompt_len)]
            try:
                stream = engine.submit(
                    prompt, max_tokens=max_tokens, tenant=tenant,
                    priority=1 if tenant == "paid" else 0,
                )
            except BackPressureError as e:
                sheds.append((tenant, e.retry_after_s))
                continue
            rec = {"tenant": tenant, "submitted": time.perf_counter()}
            recs.append(rec)
            t = threading.Thread(target=_drain, args=(stream, rec),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=900)
        stats = engine.stats()
    finally:
        engine.shutdown()

    errors = [r for r in recs if "error" in r]
    assert not errors, f"{len(errors)} request(s) failed: {errors[0]['error']}"
    by_tenant = {}
    for r in recs:
        if r["ttft_engine"] is not None:
            by_tenant.setdefault(r["tenant"], []).append(r["ttft_engine"])
    paid_ttfts = by_tenant.get("paid", [])
    paid_attainment = (
        sum(1 for t in paid_ttfts if t <= ttft_slo_s) / len(paid_ttfts)
        if paid_ttfts else 0.0
    )
    # the flood MUST be shed, every shed typed with an honest estimate
    assert sheds, "flooding tenant was never shed"
    assert all(t == "free" for t, _ in sheds), "paying tenant was shed"
    assert all(r is not None and r > 0 for _, r in sheds), \
        "shed without a computed Retry-After"
    assert paid_attainment >= 0.95, (
        f"paid TTFT SLO attainment {paid_attainment:.3f} < 0.95 "
        f"(p99={_percentile(paid_ttfts, 0.99):.3f}s vs {ttft_slo_s}s)"
    )
    drill = _preemption_drill(config, params)
    tenancy.reset()
    _emit_result({
        "metric": "serve_multitenant_paid_slo_attainment",
        "value": round(paid_attainment, 4),
        "unit": "fraction",
        "ttft_slo_s": ttft_slo_s,
        "paid_requests": len(paid_ttfts),
        "paid_p50_ttft_s": round(_percentile(paid_ttfts, 0.50), 4),
        "paid_p99_ttft_s": round(_percentile(paid_ttfts, 0.99), 4),
        "free_admitted": len(by_tenant.get("free", [])),
        "free_p99_ttft_s": round(
            _percentile(by_tenant.get("free", []), 0.99), 4),
        "free_shed_typed_429": len(sheds),
        "shed_retry_after_s_max": round(max(r for _, r in sheds), 3),
        "engine_shed_total": stats.get("shed", 0.0),
        "lane_preemptions": drill["lane_preemptions"],
        "lane_resumes": drill["lane_resumes"],
        "preempted_pages": drill["preempted_pages"],
        "token_exact_resume": drill["token_exact_resume"],
        "pages_restored": drill["pages_restored"],
        "arrival_rate_req_s": {"paid": paid_rate, "free": free_rate},
        "quota": {"free_rps": 30.0, "free_burst": 12.0},
        "weights": {"paid": 4.0, "free": 1.0},
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
    })


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard the engine over a "
                         "tp mesh of this many devices (1 = single device)")
    ap.add_argument("--model", default=None,
                    help="default: gpt2-small on TPU, llama-tiny on CPU")
    ap.add_argument("--requests", type=int, default=None,
                    help="open-loop request count (default 192 TPU / 64 CPU)")
    ap.add_argument("--rate", type=float, default=None,
                    help="Poisson arrival rate, req/s (default 24 TPU / "
                         "500 CPU — the CPU profile saturates the engine)")
    ap.add_argument("--prompt-len", type=int, default=None)
    ap.add_argument("--max-tokens", type=int, default=None)
    ap.add_argument("--system-len", type=int, default=None,
                    help="shared system-prompt length (tokens)")
    ap.add_argument("--n-system", type=int, default=3,
                    help="number of distinct shared system prompts")
    ap.add_argument("--shared-frac", type=float, default=0.75,
                    help="fraction of requests using a shared system prompt")
    ap.add_argument("--slots", type=int, default=None,
                    help="engine lanes (default 8 TPU / 16 CPU)")
    ap.add_argument("--pages", type=int, default=None)
    ap.add_argument("--page-size", type=int, default=None,
                    help="KV page size in tokens (default 64 TPU / 16 CPU)")
    ap.add_argument("--chunk-pages", type=int, default=None,
                    help="prefill chunk size in pages (default 4 TPU / 2 CPU)")
    ap.add_argument("--decode-block-steps", type=int, default=None,
                    help="decode steps per dispatched block (default 24 TPU "
                         "/ 4 CPU; must be < max-tokens for TPOT to be "
                         "measurable)")
    ap.add_argument("--max-seq", type=int, default=None,
                    help="override the model's max_seq (rope models only "
                         "need this to extend the position table; 0 keeps "
                         "the model default). CPU default 512 so the tiny "
                         "model fits a production-length system prompt.")
    ap.add_argument("--speculative", action="store_true",
                    help="run the speculative-decoding drill: spec off vs "
                         "replay (high-acceptance) vs adversarial "
                         "(all-reject) on one decode-bound workload")
    ap.add_argument("--spec-tokens", type=int, default=3,
                    help="draft tokens per verify round in the "
                         "--speculative drill")
    ap.add_argument("--openai", action="store_true",
                    help="drive the workload through the OpenAI-compatible "
                         "HTTP endpoint (/v1/completions) instead of the "
                         "engine API")
    ap.add_argument("--chaos", action="store_true",
                    help="run through a 2-replica serve deployment and kill "
                         "one replica mid-run (recovery drill)")
    ap.add_argument("--forensics-overhead", action="store_true",
                    help="A/B the request-forensics recorder: the same "
                         "open-loop workload with reqlog off vs on; "
                         "reports the tokens/s ratio (budget: >= 0.98)")
    ap.add_argument("--multitenant", action="store_true",
                    help="run the multi-tenant overload drill: a flooding "
                         "quota-limited tenant vs a paying weighted/"
                         "prioritized tenant on one engine, plus the "
                         "lane-preemption sub-drill")
    args = ap.parse_args()
    _resolve_profile(args)
    if args.multitenant:
        bench_multitenant(args)
        return
    if args.openai:
        _clamp_to_model(args)
        bench_openai(args)
        return
    if args.chaos:
        _clamp_to_model(args)
        bench_chaos(args)
        return

    import dataclasses

    from ray_tpu.models import get_config, init_params

    config = get_config(args.model)
    if args.max_seq:
        config = dataclasses.replace(config, max_seq=args.max_seq)
    mesh = None
    if args.tp > 1:
        from ray_tpu.parallel import MeshSpec, build_mesh

        mesh = build_mesh(
            MeshSpec(tp=args.tp), devices=jax.devices()[: args.tp]
        )
    params = init_params(config, jax.random.PRNGKey(0))

    if args.speculative:
        bench_speculative(args, config, params, mesh)
        return
    if args.forensics_overhead:
        bench_forensics(args, config, params, mesh)
        return

    base = _run_open_loop(args, config, params, mesh, prefix_cache=False)
    cached = _run_open_loop(args, config, params, mesh, prefix_cache=True)
    n_chips = max(1, args.tp)
    _emit_result({
        "metric": "serve_open_loop_tokens_per_s_per_chip",
        "value": round(cached["tokens_per_s"] / n_chips, 1),
        "unit": "tok/s/chip",
        # prefix-cache speedup on the shared-prefix mix
        "vs_baseline": round(
            cached["tokens_per_s"] / max(1e-9, base["tokens_per_s"]), 3
        ),
        "p50_ttft_s": round(cached["p50_ttft_s"], 4),
        "p99_ttft_s": round(cached["p99_ttft_s"], 4),
        "p50_tpot_s": round(cached["p50_tpot_s"], 5),
        "prefix_hit_rate": round(cached["prefix_hit_rate"], 3),
        "mixed_ticks": cached["mixed_ticks"],
        "baseline_mixed_ticks": base["mixed_ticks"],
        "baseline_tokens_per_s": round(base["tokens_per_s"], 1),
        "baseline_p50_ttft_s": round(base["p50_ttft_s"], 4),
        "baseline_p99_ttft_s": round(base["p99_ttft_s"], 4),
        "requests": args.requests,
        "arrival_rate_req_s": args.rate,
        "shared_frac": args.shared_frac,
        "prompt_len": args.prompt_len,
        "system_len": args.system_len,
        "max_tokens": args.max_tokens,
        "page_size": args.page_size,
        "chunk_pages": args.chunk_pages,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "tp": args.tp,
    })


def bench_chaos(args) -> None:
    """Open-loop workload against a 2-replica serve deployment with one
    replica killed mid-run: the drill passes when the controller restarts
    it, the router fails over, and EVERY request completes."""
    import ray_tpu
    from ray_tpu import serve as serve_mod
    from ray_tpu.serve import api as serve_api
    from ray_tpu.serve.llm import build_llm_app

    ray_tpu.init(detect_accelerators=True)
    handle = serve_mod.run(
        build_llm_app(args.model, name="bench-llm", num_replicas=2,
                      max_slots=args.slots, paged=True),
        name="bench-llm",
    )
    from ray_tpu.models import get_config as _get_config

    requests, _ = _build_workload(args, _get_config(args.model).vocab_size)
    results: dict = {}

    def post(i, prompt):
        try:
            out = ray_tpu.get(
                handle.generate.remote(
                    {"prompt_tokens": prompt, "max_tokens": args.max_tokens}
                ),
                timeout=900,
            )
            results[i] = len(out["tokens"])
        except Exception as exc:  # noqa: BLE001
            results[i] = repr(exc)

    try:
        post(-1, requests[0][1])  # warmup compiles
        threads = []
        kill_after = len(requests) // 2
        t0 = time.perf_counter()
        for i, (offset, prompt) in enumerate(requests):
            now = time.perf_counter() - t0
            if offset > now:
                time.sleep(offset - now)
            t = threading.Thread(target=post, args=(i, prompt), daemon=True)
            t.start()
            threads.append(t)
            if i == kill_after:
                state = serve_api._controller._states["bench-llm"]
                ray_tpu.kill(state.replicas[-1])
        for t in threads:
            t.join(timeout=900)
        elapsed = time.perf_counter() - t0
        completed = [v for v in results.values() if isinstance(v, int)]
        _emit_result({
            "metric": "serve_chaos_open_loop_req_per_s",
            "value": round(len(requests) / elapsed, 2),
            "unit": "req/s",
            "vs_baseline": round(len(completed) / (len(requests) + 1), 3),
            "completed": len(completed),
            "failed": len(results) - len(completed),
            "replica_killed": True,
            "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        })
    finally:
        serve_mod.shutdown()
        ray_tpu.shutdown()


def bench_openai(args) -> None:
    """Same open-loop arrivals, driven through the OpenAI HTTP surface:
    measures the full ingress path (HTTP + schema translation + serve
    routing + engine). TTFT is not observable per-request without SSE
    timing, so this reports req/s and decode tok/s through the endpoint."""
    import urllib.request

    import ray_tpu
    from ray_tpu import serve as serve_mod
    from ray_tpu.serve.llm import serve_openai

    ray_tpu.init(detect_accelerators=True)
    frontend = serve_openai(
        model=args.model, paged=True, max_slots=args.slots,
        tensor_parallel=args.tp,
    )
    url = f"http://127.0.0.1:{frontend.port}/v1/completions"
    from ray_tpu.models import get_config as _get_config

    requests, _ = _build_workload(args, _get_config(args.model).vocab_size)

    def post(i, prompt, results):
        req = urllib.request.Request(
            url,
            data=json.dumps({
                "model": args.model, "prompt": prompt,
                "max_tokens": args.max_tokens, "temperature": 0.0,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=900) as r:
            results[i] = json.loads(r.read())

    try:
        results: dict = {}
        post(-1, requests[0][1], results)  # warmup compiles
        threads = []
        t0 = time.perf_counter()
        for i, (offset, prompt) in enumerate(requests):
            now = time.perf_counter() - t0
            if offset > now:
                time.sleep(offset - now)
            t = threading.Thread(target=post, args=(i, prompt, results))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=900)
        elapsed = time.perf_counter() - t0
        done = [results[i] for i in range(len(requests)) if i in results]
        assert len(done) == len(requests), f"only {len(done)} completed"
        assert all(
            r["usage"]["completion_tokens"] == args.max_tokens for r in done
        )
        _emit_result({
            "metric": "serve_openai_http_req_per_s",
            "value": round(len(requests) / elapsed, 2),
            "unit": "req/s",
            "vs_baseline": 0.0,
            "decode_tokens_per_s": round(
                len(requests) * args.max_tokens / elapsed, 1
            ),
            "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
            "tp": args.tp,
        })
    finally:
        frontend.stop()
        serve_mod.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
