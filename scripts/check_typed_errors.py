#!/usr/bin/env python
"""Static check: typed-error discipline in the serve path.

Two rules:

1. No bare ``except:`` anywhere under ``ray_tpu/serve/`` — a bare
   except swallows the typed resilience errors (BackPressureError,
   RequestTimeoutError, ...) the router and HTTP layers dispatch on,
   silently converting a failover/shed/deadline signal into a hang or a
   generic 500. Catch a named exception class instead (``except
   Exception`` at an explicitly-marked boundary is fine).
2. Every exception class defined in ``ray_tpu/core/exceptions.py`` is
   exported from the top-level ``ray_tpu`` package, so callers can
   always catch framework errors without reaching into core internals.

Exits non-zero listing violations; run by tier-1 via
tests/test_serve_resilience.py (next to check_metrics_names.py).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_BARE_EXCEPT = re.compile(r"^\s*except\s*:")
_EXC_CLASS = re.compile(r"^class\s+(\w+)\s*\(", re.MULTILINE)


def check_bare_except(serve_root: Path):
    errors = []
    for path in sorted(serve_root.rglob("*.py")):
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _BARE_EXCEPT.match(line):
                errors.append(
                    f"{path}:{lineno}: bare 'except:' in the serve path — "
                    "catch a named exception class"
                )
    return errors


def check_exports(package_root: Path):
    errors = []
    exc_src = (package_root / "core" / "exceptions.py").read_text()
    init_src = (package_root / "__init__.py").read_text()
    for name in _EXC_CLASS.findall(exc_src):
        if not re.search(rf"\b{re.escape(name)}\b", init_src):
            errors.append(
                f"core/exceptions.py defines {name} but ray_tpu/__init__.py "
                "does not export it"
            )
    return errors


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else (
        Path(__file__).resolve().parent.parent / "ray_tpu"
    )
    errors = check_bare_except(root / "serve") + check_exports(root)
    for err in errors:
        print(f"check_typed_errors: {err}", file=sys.stderr)
    if errors:
        print(f"check_typed_errors: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_typed_errors: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
