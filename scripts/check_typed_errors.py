#!/usr/bin/env python
"""Thin compatibility shim over scripts/raylint (rule: typed-errors).

The logic lives in scripts/raylint/rules_legacy.py; this entry point
keeps the historical CLI (`python scripts/check_typed_errors.py [root]`)
and module API (check_bare_except/check_exports) for existing tier-1
wiring. Repo-wide enforcement runs through `python -m scripts.raylint`
(tests/test_raylint.py).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from scripts.raylint.rules_legacy import (  # noqa: E402,F401 - compat API
    check_bare_except,
    check_exports,
)


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else _REPO / "ray_tpu"
    errors = check_bare_except(root / "serve") + check_exports(root)
    for err in errors:
        print(f"check_typed_errors: {err}", file=sys.stderr)
    if errors:
        print(f"check_typed_errors: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_typed_errors: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
