"""Repo tooling package: static checks live in scripts/raylint; the
top-level check_*.py files are thin compatibility shims over it."""
