"""Text and JSON reporters over an engine.RunResult."""

from __future__ import annotations

from typing import List

from .engine import RunResult


def render_text(result: RunResult, *, show_baselined: bool = False) -> str:
    """Human report: one `path:line: [rule] message` per finding, then a
    per-rule count summary (the tier-1 failure message names rule and
    file:line straight from this)."""
    out: List[str] = []
    for f in result.findings:
        out.append(f"{f.location}: [{f.rule}] {f.message}")
    if show_baselined:
        for f in result.baselined:
            out.append(f"{f.location}: [{f.rule}] (baselined) {f.message}")
    for entry in result.stale_baseline:
        out.append(
            f"stale baseline entry: [{entry.get('rule')}] "
            f"{entry.get('path')}:{entry.get('line')} no longer matches — "
            f"regenerate with --write-baseline"
        )
    total = len(result.findings)
    per_rule = ", ".join(
        f"{name}={count}" for name, count in sorted(result.counts.items())
    )
    status = "FAIL" if total else "ok"
    out.append(
        f"raylint: {status} — {total} finding(s) "
        f"[{per_rule}] "
        f"({len(result.baselined)} baselined, {result.suppressed} suppressed"
        + (f", {len(result.stale_baseline)} stale baseline entr(y/ies)"
           if result.stale_baseline else "")
        + ")"
    )
    return "\n".join(out)


def render_json(result: RunResult) -> dict:
    """Machine schema (stable, versioned): counts include every ran rule
    (zeros too) so consumers can assert coverage."""
    return {
        "version": 1,
        "rules": list(result.ran_rules),
        "counts": dict(result.counts),
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
            }
            for f in result.findings
        ],
        "baselined": len(result.baselined),
        "suppressed": result.suppressed,
        "stale_baseline": list(result.stale_baseline),
        "ok": result.ok,
    }
