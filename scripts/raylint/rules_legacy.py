"""The five pre-raylint check scripts, folded in as registry rules.

Each check keeps a root-parameterized core function so the old
``scripts/check_*.py`` entry points can stay behaviour-compatible thin
shims (tier-1 fixture tests call them against temp trees), while the
registered Rule runs the same logic over the shared parsed-file cache.

Rules: typed-errors, metrics-names, atomic-writes, lazy-jax,
kernel-fallbacks — see each class's `doc` for the contract.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from pathlib import Path
from typing import Iterable, List, Tuple

from .engine import Finding, Project, Rule, register

# --------------------------------------------------------------- typed-errors

_BARE_EXCEPT = re.compile(r"^\s*except\s*:")
_EXC_CLASS = re.compile(r"^class\s+(\w+)\s*\(", re.MULTILINE)


def bare_except_lines(lines) -> List[Tuple[int, str]]:
    return [
        (lineno, "bare 'except:' in the serve path — catch a named "
                 "exception class")
        for lineno, line in enumerate(lines, 1)
        if _BARE_EXCEPT.match(line)
    ]


def check_bare_except(serve_root) -> List[str]:
    """Compat API (shim + fixture tests): old-style strings."""
    errors = []
    for path in sorted(Path(serve_root).rglob("*.py")):
        for lineno, msg in bare_except_lines(path.read_text().splitlines()):
            errors.append(f"{path}:{lineno}: {msg}")
    return errors


def missing_exception_exports(exc_src: str, init_src: str) -> List[str]:
    return [
        name for name in _EXC_CLASS.findall(exc_src)
        if not re.search(rf"\b{re.escape(name)}\b", init_src)
    ]


def check_exports(package_root) -> List[str]:
    """Compat API: every core exception class is exported top-level."""
    package_root = Path(package_root)
    exc_src = (package_root / "core" / "exceptions.py").read_text()
    init_src = (package_root / "__init__.py").read_text()
    return [
        f"core/exceptions.py defines {name} but ray_tpu/__init__.py "
        f"does not export it"
        for name in missing_exception_exports(exc_src, init_src)
    ]


@register
class TypedErrorsRule(Rule):
    name = "typed-errors"
    doc = ("No bare 'except:' under ray_tpu/serve/ (it swallows the typed "
           "resilience errors the router dispatches on); every exception "
           "class in core/exceptions.py is exported from ray_tpu.")

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.files_under("ray_tpu/serve/"):
            for lineno, msg in bare_except_lines(sf.lines):
                yield Finding(self.name, sf.rel, lineno, msg)
        exc = project.file("ray_tpu/core/exceptions.py")
        init = project.file("ray_tpu/__init__.py")
        if exc is not None and init is not None:
            for name in missing_exception_exports(exc.text, init.text):
                yield Finding(
                    self.name, exc.rel, 1,
                    f"exception class {name} is not exported from "
                    f"ray_tpu/__init__.py",
                )


# -------------------------------------------------------------- metrics-names

# literal-first-arg metric instantiations; group 1 = constructor,
# group 2 = metric name
_METRIC_PATTERN = re.compile(
    r"""(?<![\w.])(Counter|Gauge|Histogram|
        get_or_create_counter|get_or_create_gauge|get_or_create_histogram)
        \(\s*["']([^"']+)["']""",
    re.VERBOSE,
)
_DIRECT = {"Counter", "Gauge", "Histogram"}
_HISTOGRAMS = {"Histogram", "get_or_create_histogram"}
# the one module allowed to touch sampler internals (it IS the guard)
_GUARD_MODULE = "metrics.py"


def _call_text(text: str, start: int, limit: int = 4000) -> str:
    """The full call expression from the opening paren at/after `start`
    to its balanced close (string-naive: metric registrations never
    embed unbalanced parens in literals)."""
    i = text.index("(", start)
    depth = 0
    for j in range(i, min(len(text), i + limit)):
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                return text[i:j + 1]
    return text[i:i + limit]


def metric_findings(files) -> List[Tuple[str, int, str]]:
    """(relpath, lineno, message) over [(relpath, text)] file pairs."""
    errors: List[Tuple[str, int, str]] = []
    direct_sites = defaultdict(list)  # metric name -> [(rel, lineno)]
    for rel, text in files:
        lines = text.splitlines()
        for match in _METRIC_PATTERN.finditer(text):
            lineno = text.count("\n", 0, match.start()) + 1
            line = lines[lineno - 1].strip()
            if line.startswith(("class ", "def ", "#")):
                continue
            ctor, name = match.group(1), match.group(2)
            if not name.startswith("raytpu_"):
                errors.append((
                    rel, lineno,
                    f"metric {name!r} missing the raytpu_ prefix",
                ))
            if ctor in _DIRECT:
                direct_sites[name].append((rel, lineno))
            if ctor in _HISTOGRAMS:
                call = _call_text(text, match.start())
                if "boundaries" not in call:
                    errors.append((
                        rel, lineno,
                        f"histogram {name!r} registered without explicit "
                        f"boundaries= — the default buckets misfit most "
                        f"latency distributions",
                    ))
        # sampler-guard bypasses (outside the guard module)
        if rel.endswith(f"util/{_GUARD_MODULE}"):
            continue
        for lineno, line in enumerate(lines, 1):
            stripped = line.strip()
            if stripped.startswith("#"):
                continue
            if re.search(r"\._fn\(\s*\)", line):
                # samplers are zero-arg callables; `obj._fn(args)` is
                # some other attribute, not a gauge callback
                errors.append((
                    rel, lineno,
                    "direct sampler call `._fn()` bypasses the "
                    "Gauge.collect sampler-failure guard — sample through "
                    "collect()/prometheus_text()",
                ))
            if re.match(r"\s*def collect\(", line):
                errors.append((
                    rel, lineno,
                    "collect() override outside util/metrics.py — callback "
                    "gauges must go through the guarded Gauge.collect, not "
                    "reimplement it",
                ))
    for name, sites in sorted(direct_sites.items()):
        if len(sites) > 1:
            locs = ", ".join(f"{rel}:{lineno}" for rel, lineno in sites)
            errors.append((
                sites[0][0], sites[0][1],
                f"metric {name!r} directly constructed at {len(sites)} "
                f"sites ({locs}): all but the first silently shadow the "
                f"registered series — use get_or_create_*",
            ))
    return errors


def check(package_root) -> List[str]:
    """Compat API (shim + fixture tests): old-style strings."""
    package_root = Path(package_root)
    files = [
        (str(p.relative_to(package_root.parent)), p.read_text())
        for p in sorted(package_root.rglob("*.py"))
    ]
    return [
        f"{rel}:{lineno}: {msg}" for rel, lineno, msg in metric_findings(files)
    ]


@register
class MetricsNamesRule(Rule):
    name = "metrics-names"
    doc = ("Metric naming + registration discipline: raytpu_ prefix, no "
           "duplicate direct registrations, explicit histogram "
           "boundaries=, no sampler-guard bypasses.")

    def check(self, project: Project) -> Iterable[Finding]:
        files = [
            (sf.rel, sf.text) for sf in project.files_under("ray_tpu/")
        ]
        for rel, lineno, msg in metric_findings(files):
            yield Finding(self.name, rel, lineno, msg)


# -------------------------------------------------------------- atomic-writes

_OPEN_WRITE = re.compile(
    r"""open\(\s*([^,)]+),\s*(?:mode\s*=\s*)?["']wb?["']"""
)
_ATOMIC_WAIVER = re.compile(r"#\s*atomic-ok:")
_REPLACE_WINDOW = 8  # lines after the open() in which os.replace must appear


def atomic_write_lines(lines) -> List[Tuple[int, str]]:
    errors = []
    for lineno, line in enumerate(lines, 1):
        m = _OPEN_WRITE.search(line)
        if m is None:
            continue
        if _ATOMIC_WAIVER.search(line):
            continue
        path_expr = m.group(1)
        if "tmp" in path_expr.lower():
            continue  # staged write: the os.replace commit is the contract
        tail = "\n".join(lines[lineno - 1: lineno - 1 + _REPLACE_WINDOW])
        if "os.replace(" in tail:
            continue
        errors.append((
            lineno,
            f"non-atomic state write (open({path_expr.strip()}, 'w'/'wb') "
            f"without tmp + os.replace); stage to a .tmp sibling and "
            f"os.replace, or waive with '# atomic-ok: <why>'",
        ))
    return errors


def check_file(path) -> List[str]:
    """Compat API (shim + fixture tests): old-style strings."""
    path = Path(path)
    return [
        f"{path}:{lineno}: {msg}"
        for lineno, msg in atomic_write_lines(path.read_text().splitlines())
    ]


def _atomic_targets(root: Path) -> List[Path]:
    targets = sorted((root / "train").rglob("*.py"))
    gcs = root / "core" / "gcs.py"
    if gcs.exists():
        targets.append(gcs)
    return targets


@register
class AtomicWritesRule(Rule):
    name = "atomic-writes"
    doc = ("State-persisting writes in train/ and core/gcs.py must stage "
           "through tmp + os.replace (or carry an '# atomic-ok:' waiver) "
           "so a crash never leaves torn checkpoints/snapshots.")

    def check(self, project: Project) -> Iterable[Finding]:
        targets = (
            project.files_under("ray_tpu/train/")
            + [f for f in (project.file("ray_tpu/core/gcs.py"),) if f]
        )
        for sf in targets:
            for lineno, msg in atomic_write_lines(sf.lines):
                yield Finding(self.name, sf.rel, lineno, msg)


# ------------------------------------------------------------------- lazy-jax

LAZY_JAX_MODULES = (
    "ray_tpu/util/profiling.py",
    "ray_tpu/core/stats.py",
    "ray_tpu/util/tracing.py",
)


def _is_jax_import(node: ast.AST) -> bool:
    if isinstance(node, ast.Import):
        return any(
            alias.name == "jax" or alias.name.startswith("jax.")
            for alias in node.names
        )
    if isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        return mod == "jax" or mod.startswith("jax.")
    return False


def _walk_jax_imports(node, in_function, in_type_checking, out):
    for child in ast.iter_child_nodes(node):
        child_in_fn = in_function or isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        )
        child_tc = in_type_checking or (
            isinstance(node, ast.If)
            and isinstance(node.test, (ast.Name, ast.Attribute))
            and "TYPE_CHECKING" in ast.dump(node.test)
        )
        if _is_jax_import(child) and not child_in_fn and not child_tc:
            out.append(child.lineno)
        _walk_jax_imports(child, child_in_fn, child_tc, out)


def module_level_jax_imports(tree: ast.AST) -> List[int]:
    offenders: List[int] = []
    _walk_jax_imports(tree, False, False, offenders)
    return offenders


_LAZY_JAX_MSG = (
    "module-level jax import — move it inside the function that needs it "
    "(this module must import on jax-less hosts)"
)


@register
class LazyJaxRule(Rule):
    name = "lazy-jax"
    doc = ("profiling/stats/tracing are imported by jax-less observer "
           "hosts: their jax imports must stay function-local.")

    def check(self, project: Project) -> Iterable[Finding]:
        for rel in LAZY_JAX_MODULES:
            sf = project.file(rel)
            if sf is None:
                yield Finding(self.name, rel, 1, "checked module is missing")
                continue
            for lineno in module_level_jax_imports(sf.tree):
                yield Finding(self.name, sf.rel, lineno, _LAZY_JAX_MSG)


# ----------------------------------------------------------- kernel-fallbacks

REQUIRED_FLAGS = (
    "attn_pipeline",
    "dp_allreduce_dtype",
    "dp_shard_update",
    "dp_quant_block",
    # serve throughput round (ragged kernel + SLO autoscaler)
    "serve_ragged_kernel",
    "autoscale_burn_windows",
    "autoscale_pressure_floor",
)

# RayTpuConfig API that is not a flag read
_CFG_METHODS = {"set", "reset", "describe", "as_dict"}


def _uses_pltpu(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id == "pltpu":
            return True
    return False


def _pltpu_import_guarded(tree: ast.AST) -> bool:
    """The `from jax.experimental.pallas import tpu as pltpu` import must
    sit inside a try/except ImportError (or be function-local)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            handled = any(
                isinstance(h.type, ast.Name)
                and h.type.id in ("ImportError", "Exception")
                or isinstance(h.type, ast.Tuple)
                for h in node.handlers
            )
            if not handled:
                continue
            for child in ast.walk(node):
                if isinstance(child, ast.ImportFrom):
                    mod = child.module or ""
                    if mod.startswith("jax.experimental.pallas") and any(
                        a.asname == "pltpu" or a.name == "tpu"
                        for a in child.names
                    ):
                        return True
    return False


def _has_fallback_path(tree: ast.AST) -> bool:
    """A `*reference*` function (pure-XLA ground truth) or an
    `interpret=` kwarg on some call (interpret-mode driver)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if "reference" in node.name:
                return True
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "interpret":
                    return True
        if isinstance(node, ast.arg) and node.arg == "interpret":
            return True
    return False


def defined_flags(config_tree: ast.AST) -> set:
    flags = set()
    for node in ast.walk(config_tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "define_flag"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            flags.add(node.args[0].value)
    return flags


def cfg_reads(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, attr) for attribute reads on `cfg` — only in modules that
    import cfg from the config registry and never rebind the name."""
    imports_cfg = any(
        isinstance(node, ast.ImportFrom)
        and (node.module or "").endswith("config")
        and any(a.name == "cfg" for a in node.names)
        for node in ast.walk(tree)
    )
    if not imports_cfg:
        return []
    for node in ast.walk(tree):  # local rebinding shadows the registry
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "cfg":
                    return []
    return [
        (node.lineno, node.attr)
        for node in ast.walk(tree)
        if isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "cfg"
    ]


@register
class KernelFallbacksRule(Rule):
    name = "kernel-fallbacks"
    doc = ("pltpu-gated kernels keep a guarded import plus a non-TPU "
           "fallback path; every cfg.<flag> read resolves to a "
           "define_flag registration in core/config.py.")

    def check(self, project: Project) -> Iterable[Finding]:
        config = project.file("ray_tpu/core/config.py")
        flags = defined_flags(config.tree) if config is not None else set()
        if config is not None:
            for name in REQUIRED_FLAGS:
                if name not in flags:
                    yield Finding(
                        self.name, config.rel, 1,
                        f"required flag {name!r} is not registered via "
                        f"define_flag",
                    )
        for sf in project.files:
            tree = sf.tree
            if _uses_pltpu(tree):
                if not _pltpu_import_guarded(tree):
                    yield Finding(
                        self.name, sf.rel, 1,
                        "pltpu import is not guarded by try/except "
                        "ImportError — non-TPU builds must still import "
                        "this",
                    )
                if not _has_fallback_path(tree):
                    yield Finding(
                        self.name, sf.rel, 1,
                        "pltpu-gated kernels but no registered non-TPU "
                        "fallback (need a *reference* function or an "
                        "interpret= driver)",
                    )
            if flags:
                for lineno, attr in cfg_reads(tree):
                    if attr not in flags and attr not in _CFG_METHODS:
                        yield Finding(
                            self.name, sf.rel, lineno,
                            f"cfg.{attr} reads a flag that is not "
                            f"registered in core/config.py defaults",
                        )
