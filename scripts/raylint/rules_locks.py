"""Concurrency analysis passes: lock discipline over `# guarded-by:`
annotations, module-level lock acquisition-order cycles, and blocking
calls made while a lock is held.

Annotation conventions:

- ``self._attr = ...  # guarded-by: _lock`` on an attribute assignment
  inside a class declares that every access of ``self._attr`` outside
  ``__init__``/``__del__`` must happen inside a ``with self._lock:``
  block (any lock attribute name works, e.g. ``_inst_lock``).
  ``# guarded-by: _lock|_free`` accepts either name — a Condition and
  the Lock it wraps are one guard under two names.
- ``def _helper(self):  # holds-lock: _lock`` on a ``def`` line declares
  the method is only ever called with ``_lock`` already held; its body
  is analyzed as if the lock were acquired (the caller side still gets
  checked at its own ``with``).

Held tracking is intentionally syntactic. For guarded-attribute checks
any ``with`` item's final name counts as an acquisition (guards are
matched by their DECLARED name); for lock-order and blocking-under-lock
only names containing ``lock`` (case-insensitive) count. Nested function
bodies (closures, lambdas, callbacks) are NOT treated as running under
the enclosing ``with`` — they usually run later on another thread.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding, Project, Rule, SourceFile, register

_GUARDED_BY = re.compile(r"#\s*guarded-by:\s*([\w.|]+)")
_HOLDS_LOCK = re.compile(r"#\s*holds-lock:\s*([\w.,\s]+)")
_LOCKISH = re.compile(r"lock", re.I)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_NESTED_SCOPE = _FUNC_NODES + (ast.Lambda,)


def _tail_name(node: ast.AST) -> Optional[str]:
    """Final attribute/name of an expression: self._lock -> '_lock'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted source form of a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _with_locks(stmt: ast.With) -> List[Tuple[str, str]]:
    """(tail_name, dotted) for every lock-ish context manager acquired by
    this `with` statement."""
    out = []
    for item in stmt.items:
        tail = _tail_name(item.context_expr)
        if tail and _LOCKISH.search(tail):
            out.append((tail, _dotted(item.context_expr) or tail))
    return out


def _holds_locks(sf: SourceFile, fn: ast.AST) -> Set[str]:
    """Lock names a `# holds-lock:` comment on the def line grants."""
    line = sf.lines[fn.lineno - 1] if fn.lineno <= len(sf.lines) else ""
    m = _HOLDS_LOCK.search(line)
    if not m:
        return set()
    return {part.strip() for part in m.group(1).split(",") if part.strip()}


def _guarded_attrs(sf: SourceFile, cls: ast.ClassDef) -> Dict[str, frozenset]:
    """attr -> acceptable guard names, from `# guarded-by:` comments
    attached to `self.<attr> = ...` (or class-level `<attr> = ...`)
    assignment lines inside the class. `# guarded-by: _lock|_free`
    accepts either name (a Condition and the Lock it wraps are one
    guard under two names)."""
    annotated: Dict[int, frozenset] = {}
    for lineno, line in enumerate(sf.lines, 1):
        m = _GUARDED_BY.search(line)
        if m:
            annotated[lineno] = frozenset(
                part.split(".")[-1]
                for part in m.group(1).split("|") if part
            )
    if not annotated:
        return {}
    guarded: Dict[str, frozenset] = {}
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            lock = annotated.get(node.lineno)
            if lock is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in ("self", "cls")
                ):
                    guarded[t.attr] = lock
                elif isinstance(t, ast.Name):
                    guarded[t.id] = lock
    return guarded


class _GuardWalker:
    """Walk one method body tracking which lock tail-names are held,
    flagging guarded-attribute accesses made without their guard."""

    def __init__(self, sf: SourceFile, cls_name: str,
                 guarded: Dict[str, str], rule: str):
        self.sf = sf
        self.cls_name = cls_name
        self.guarded = guarded
        self.rule = rule
        self.findings: List[Finding] = []

    def walk(self, node: ast.AST, held: Set[str]) -> None:
        """Process `node` itself, then descend; `with` bodies re-enter
        here so nested acquisitions stack correctly."""
        if isinstance(node, ast.With):
            # guards are matched by the DECLARED name, so any context
            # manager counts (Conditions like `with self._free:` guard
            # state too, without 'lock' in their name)
            acquired = {
                tail for tail in (
                    _tail_name(item.context_expr) for item in node.items
                ) if tail
            }
            for item in node.items:  # the with-expr itself runs unheld
                self.walk(item.context_expr, held)
            for stmt in node.body:
                self.walk(stmt, held | acquired)
            return
        if isinstance(node, ast.Attribute):
            self._check_attr(node, held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _NESTED_SCOPE):
                continue  # closures run later, usually without the lock
            self.walk(child, held)

    def _check_attr(self, node: ast.Attribute, held: Set[str]) -> None:
        if not (
            isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            return
        guard = self.guarded.get(node.attr)
        if guard is None or guard & held:
            return
        spec = "|".join(sorted(guard))
        main = sorted(guard)[0]
        self.findings.append(Finding(
            self.rule, self.sf.rel, node.lineno,
            f"{self.cls_name}.{node.attr} is declared guarded-by "
            f"{spec} but is accessed without holding it "
            f"(wrap in `with self.{main}:` or mark the enclosing "
            f"method `# holds-lock: {main}`)",
        ))


def lock_discipline_findings(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _guarded_attrs(sf, cls)
        if not guarded:
            continue
        for fn in cls.body:
            if not isinstance(fn, _FUNC_NODES):
                continue
            if fn.name in ("__init__", "__del__"):
                continue  # construction/teardown precede or outlive sharing
            walker = _GuardWalker(sf, cls.name, guarded, "lock-discipline")
            walker.walk(fn, _holds_locks(sf, fn))
            findings.extend(walker.findings)
    return findings


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    doc = ("Attributes annotated `# guarded-by: <lock>` may only be "
           "accessed inside `with self.<lock>:` (or from a method marked "
           "`# holds-lock: <lock>`).")

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.files_under("ray_tpu/"):
            yield from lock_discipline_findings(sf)


# ----------------------------------------------------------------- lock-order


def _order_edges(sf: SourceFile) -> List[Tuple[str, str, int]]:
    """(outer_lock, inner_lock, lineno) acquisition edges per module;
    lock identity is `<ClassName>.<dotted expr>` so same-named locks in
    different classes don't alias."""
    edges: List[Tuple[str, str, int]] = []

    def qualify(dotted: str, cls: Optional[str]) -> str:
        if dotted.startswith(("self.", "cls.")) and cls:
            return f"{cls}.{dotted.split('.', 1)[1]}"
        return dotted

    def walk(node: ast.AST, held: List[str], cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            cls = node.name
        if isinstance(node, ast.With):
            acquired = [
                qualify(dotted, cls) for _, dotted in _with_locks(node)
            ]
            for lock in acquired:
                for outer in held:
                    if outer != lock:
                        edges.append((outer, lock, node.lineno))
            for stmt in node.body:
                walk(stmt, held + acquired, cls)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held, cls)

    walk(sf.tree, [], None)
    return edges


def _find_cycles(edges: List[Tuple[str, str, int]]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b, _ in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    seen_cycles: Set[frozenset] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(path + [start])
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))
    return cycles


def lock_order_findings(sf: SourceFile) -> List[Finding]:
    edges = _order_edges(sf)
    if not edges:
        return []
    findings = []
    for cycle in _find_cycles(edges):
        first_edge_line = min(
            lineno for a, b, lineno in edges
            if a in cycle and b in cycle
        )
        findings.append(Finding(
            "lock-order", sf.rel, first_edge_line,
            "lock acquisition order cycle: " + " -> ".join(cycle) +
            " — two threads taking these locks in opposite orders "
            "deadlock; pick one global order",
        ))
    return findings


@register
class LockOrderRule(Rule):
    name = "lock-order"
    doc = ("Within a module, nested `with <lock>:` acquisitions must form "
           "a DAG — opposite-order acquisition of two locks is a "
           "deadlock.")

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.files_under("ray_tpu/"):
            yield from lock_order_findings(sf)


# --------------------------------------------------------- blocking-under-lock

_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output", "Popen"}


def _blocking_reason(call: ast.Call, from_time_sleep: bool) -> Optional[str]:
    """Why this call blocks, or None. Heuristics tuned for this tree:

    - time.sleep / bare sleep (when imported from time)
    - zero-positional-arg .join() — thread/queue join; str.join takes one
    - .result() / .wait() — future/event waits
    - .get(timeout=...) / .get(block=...) — queue-style blocking gets
    - subprocess.run/call/check_call/check_output/Popen
    - .call(...) on an rpc/client/stub-named receiver (RpcClient.call)
    - api.get / ray_tpu.get — object-store waits
    - .recv( / .accept( — socket waits
    - open() / os.open() — file I/O
    - pickle/cloudpickle dump(s)/load(s) — unbounded serialization work
    """
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "sleep" and from_time_sleep:
            return "sleep() (time.sleep)"
        if func.id == "open":
            return "open() (file I/O)"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = _tail_name(func.value)
    if attr == "sleep" and recv == "time":
        return "time.sleep()"
    if recv == "os" and attr == "open":
        return "os.open() (file I/O)"
    if recv in ("pickle", "cloudpickle") and attr in (
        "dump", "dumps", "load", "loads"
    ):
        return (f"{recv}.{attr}() (serializing arbitrary object graphs "
                f"stalls every other holder)")
    if recv == "subprocess" and attr in _SUBPROCESS_BLOCKING:
        return f"subprocess.{attr}()"
    if attr == "join" and not call.args:
        return ".join() (thread/queue join; str.join takes an argument)"
    if attr == "result" and not call.args:
        return ".result() (future wait)"
    if attr == "wait":
        return ".wait()"
    if attr == "get":
        if recv in ("api", "ray_tpu"):
            return f"{recv}.get() (object-store wait)"
        if any(kw.arg in ("timeout", "block") for kw in call.keywords):
            return ".get(timeout=/block=) (queue-style blocking get)"
        return None
    if attr == "call" and recv and re.search(r"rpc|client|stub", recv, re.I):
        return f"{recv}.call() (synchronous RPC)"
    if attr in ("recv", "accept") and recv not in ("re", "random"):
        return f".{attr}() (socket wait)"
    return None


def blocking_under_lock_findings(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    imports_time_sleep = any(
        isinstance(node, ast.ImportFrom)
        and node.module == "time"
        and any(a.name == "sleep" for a in node.names)
        for node in ast.walk(sf.tree)
    )

    def walk(node: ast.AST, held: List[str]) -> None:
        if held and isinstance(node, _NESTED_SCOPE):
            # a closure/callback body runs later, not under this lock
            walk(node, [])
            return
        if isinstance(node, ast.With):
            acquired = [tail for tail, _ in _with_locks(node)]
            for item in node.items:
                walk(item.context_expr, held)
            for stmt in node.body:
                walk(stmt, held + acquired)
            return
        if isinstance(node, ast.Call) and held:
            reason = _blocking_reason(node, imports_time_sleep)
            if reason is not None:
                findings.append(Finding(
                    "blocking-under-lock", sf.rel, node.lineno,
                    f"blocking call {reason} while holding "
                    f"{', '.join(sorted(set(held)))} — waits under a "
                    f"lock serialize every other holder and can "
                    f"deadlock; move the wait outside the critical "
                    f"section",
                ))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    walk(sf.tree, [])
    return findings


@register
class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    doc = ("No sleeps, joins, future/object waits, subprocess invocations "
           "or synchronous RPCs while holding a lock — the control-plane "
           "deadlock shape (heartbeat and router paths are the most "
           "exposed).")

    def check(self, project: Project) -> Iterable[Finding]:
        for sf in project.files_under("ray_tpu/"):
            yield from blocking_under_lock_findings(sf)
