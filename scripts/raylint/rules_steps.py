"""step-phase: every training-forensics mark names a registered phase.

The training forensics plane (ray_tpu/train/steplog.py) is TYPED the
same way the request plane is: consumers — the per-rank waterfall, the
cross-rank skew matrix, the watchdog's dominant-bucket attribution, the
``raytpu_train_step_seconds`` histograms — key off the ``phase`` field,
and the exact-sum invariant (buckets sum to step wall time) only holds
when every mark lands in a known bucket. A typo'd phase silently drops
out of every downstream view AND skews the ``other`` remainder. This
rule holds every ``steplog.mark(...)`` / imported ``mark(...)`` /
``steplog.log().mark(...)`` call site under ``ray_tpu/`` to the
registry:

- the phase argument (1st positional, or ``phase=``) must be a string
  literal — dynamic phases defeat static checking;
- the literal must be registered: a key of the ``STEP_PHASES`` dict
  literal in train/steplog.py, or the first argument of any
  ``register_step_phase("...")`` call in the tree.

``ray_tpu/train/steplog.py`` itself is exempt (it defines the plumbing
that forwards ``phase`` through).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import Finding, Project, Rule, SourceFile, register

STEPLOG_MODULE_REL = "ray_tpu/train/steplog.py"


def registered_step_phases(project: Project) -> Set[str]:
    """The static phase registry: STEP_PHASES literal keys plus every
    register_step_phase("...") string-literal call in the tree."""
    phases: Set[str] = set()
    steplog_sf = project.file(STEPLOG_MODULE_REL)
    if steplog_sf is not None:
        for node in ast.walk(steplog_sf.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):  # STEP_PHASES: Dict[...] = {}
                targets = [node.target]
            else:
                continue
            if (any(isinstance(t, ast.Name) and t.id == "STEP_PHASES"
                    for t in targets)
                    and isinstance(node.value, ast.Dict)):
                for key in node.value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        phases.add(key.value)
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            is_registrar = (
                isinstance(func, ast.Name)
                and func.id == "register_step_phase"
            ) or (
                isinstance(func, ast.Attribute)
                and func.attr == "register_step_phase"
            )
            if (is_registrar
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                phases.add(node.args[0].value)
    return phases


def _steplog_mark_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to steplog's mark via `from ... import`."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        module = node.module or ""
        if not (module == "steplog" or module.endswith(".steplog")
                or module == "train.steplog"):
            continue
        for alias in node.names:
            if alias.name == "mark":
                aliases.add(alias.asname or alias.name)
    return aliases


def _is_steplog_receiver(func: ast.AST) -> bool:
    """True for `steplog.mark` / `<x>.steplog.mark` /
    `steplog.log().mark` receivers (the module alias and the StepLog
    singleton reached THROUGH the module — a bare `log()` stays the
    request plane's receiver, request-phase covers it)."""
    if isinstance(func, ast.Name) and func.id == "steplog":
        return True
    if isinstance(func, ast.Attribute) and func.attr == "steplog":
        return True
    # steplog.log().mark — the singleton factory, module-qualified
    return (isinstance(func, ast.Call)
            and isinstance(func.func, ast.Attribute)
            and func.func.attr == "log"
            and _is_steplog_receiver(func.func.value))


def step_mark_call_findings(sf: SourceFile, phases: Set[str],
                            rule_name: str = "step-phase") -> List[Finding]:
    aliases = _steplog_mark_aliases(sf.tree)
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_mark = (isinstance(func, ast.Name) and func.id in aliases) or (
            isinstance(func, ast.Attribute) and func.attr == "mark"
            and _is_steplog_receiver(func.value)
        )
        if not is_mark:
            continue
        msg = _check_step_phase_arg(node, phases)
        if msg is not None:
            out.append(Finding(rule_name, sf.rel, node.lineno, msg))
    return out


def _check_step_phase_arg(call: ast.Call, phases: Set[str]) -> Optional[str]:
    phase_kw = next((kw for kw in call.keywords if kw.arg == "phase"), None)
    if phase_kw is None:
        # positional phase: mark(phase, dur_s, ...)
        if call.args:
            phase_kw = ast.keyword(arg="phase", value=call.args[0])
        else:
            return ("steplog.mark without a phase: pass a registered "
                    "step phase (see STEP_PHASES in train/steplog.py)")
    if not (isinstance(phase_kw.value, ast.Constant)
            and isinstance(phase_kw.value.value, str)):
        return ("steplog.mark phase must be a string literal so the "
                "registry check stays static")
    phase = phase_kw.value.value
    if phase not in phases:
        return (f"steplog.mark phase={phase!r} is not registered in "
                f"STEP_PHASES (train/steplog.py) or via "
                f"register_step_phase")
    return None


@register
class StepPhaseRule(Rule):
    name = "step-phase"
    doc = ("every steplog.mark call site in ray_tpu/ passes a phase "
           "string literal registered in the step-phase schema")

    def check(self, project: Project) -> Iterable[Finding]:
        phases = registered_step_phases(project)
        for sf in project.files_under("ray_tpu/"):
            if sf.rel == STEPLOG_MODULE_REL:
                continue  # the plumbing that forwards phase through
            yield from step_mark_call_findings(sf, phases, self.name)
