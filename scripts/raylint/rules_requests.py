"""request-phase: every request-forensics mark names a registered phase.

The request forensics plane (ray_tpu/serve/reqlog.py) is TYPED the same
way the flight recorder is: consumers — the waterfall renderer, the
TTFT decomposition, ``state.list_requests`` terminal detection — key
off the ``phase`` field, so a mark with a typo'd phase silently drops
out of every downstream view (worse: a misspelled terminal phase leaves
the request forever-pending). This rule holds every ``reqlog.mark(...)``
/ ``mark(...)`` / ``log().mark(...)`` call site under ``ray_tpu/`` to
the registry:

- the phase argument (2nd positional, or ``phase=``) must be a string
  literal — dynamic phases defeat static checking;
- the literal must be registered: a key of the ``PHASES`` dict literal
  in serve/reqlog.py, or the first argument of any
  ``register_phase("...")`` call in the tree.

``ray_tpu/serve/reqlog.py`` itself is exempt (it defines the plumbing
that forwards ``phase`` through).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import Finding, Project, Rule, SourceFile, register

REQLOG_MODULE_REL = "ray_tpu/serve/reqlog.py"


def registered_phases(project: Project) -> Set[str]:
    """The static phase registry: PHASES literal keys plus every
    register_phase("...") string-literal call in the tree."""
    phases: Set[str] = set()
    reqlog_sf = project.file(REQLOG_MODULE_REL)
    if reqlog_sf is not None:
        for node in ast.walk(reqlog_sf.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):  # PHASES: Dict[...] = {}
                targets = [node.target]
            else:
                continue
            if (any(isinstance(t, ast.Name) and t.id == "PHASES"
                    for t in targets)
                    and isinstance(node.value, ast.Dict)):
                for key in node.value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        phases.add(key.value)
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "register_phase"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                phases.add(node.args[0].value)
    return phases


def _mark_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to reqlog's mark via `from ... import`."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        module = node.module or ""
        if not (module == "reqlog" or module.endswith(".reqlog")
                or module == "serve.reqlog"):
            continue
        for alias in node.names:
            if alias.name == "mark":
                aliases.add(alias.asname or alias.name)
    return aliases


def _is_reqlog_receiver(func: ast.AST) -> bool:
    """True for `reqlog.mark` / `<x>.reqlog.mark` / `log().mark`
    receivers (the module alias and the singleton factory)."""
    if isinstance(func, ast.Name) and func.id == "reqlog":
        return True
    if isinstance(func, ast.Attribute) and func.attr == "reqlog":
        return True
    # log().mark / reqlog.log().mark — the RequestLog singleton
    return (isinstance(func, ast.Call)
            and ((isinstance(func.func, ast.Name)
                  and func.func.id == "log")
                 or (isinstance(func.func, ast.Attribute)
                     and func.func.attr == "log")))


def mark_call_findings(sf: SourceFile, phases: Set[str],
                       rule_name: str = "request-phase") -> List[Finding]:
    aliases = _mark_aliases(sf.tree)
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_mark = (isinstance(func, ast.Name) and func.id in aliases) or (
            isinstance(func, ast.Attribute) and func.attr == "mark"
            and _is_reqlog_receiver(func.value)
        )
        if not is_mark:
            continue
        msg = _check_phase_arg(node, phases)
        if msg is not None:
            out.append(Finding(rule_name, sf.rel, node.lineno, msg))
    return out


def _check_phase_arg(call: ast.Call, phases: Set[str]) -> Optional[str]:
    phase_kw = next((kw for kw in call.keywords if kw.arg == "phase"), None)
    if phase_kw is None:
        # positional phase: mark(request_id, phase, ...)
        if len(call.args) >= 2:
            phase_kw = ast.keyword(arg="phase", value=call.args[1])
        else:
            return ("reqlog.mark without a phase: pass a registered "
                    "request phase (see PHASES in serve/reqlog.py)")
    if not (isinstance(phase_kw.value, ast.Constant)
            and isinstance(phase_kw.value.value, str)):
        return ("reqlog.mark phase must be a string literal so the "
                "registry check stays static")
    phase = phase_kw.value.value
    if phase not in phases:
        return (f"reqlog.mark phase={phase!r} is not registered in "
                f"PHASES (serve/reqlog.py) or via register_phase")
    return None


@register
class RequestPhaseRule(Rule):
    name = "request-phase"
    doc = ("every reqlog.mark call site in ray_tpu/ passes a phase "
           "string literal registered in the request-phase schema")

    def check(self, project: Project) -> Iterable[Finding]:
        phases = registered_phases(project)
        for sf in project.files_under("ray_tpu/"):
            if sf.rel == REQLOG_MODULE_REL:
                continue  # the plumbing that forwards phase through
            yield from mark_call_findings(sf, phases, self.name)
