"""gcs-durable-mutations: every durable GCS table write is journaled.

The head fault-tolerance contract (core/gcs.py) is that an acknowledged
write survives a head SIGKILL: the WAL records each mutation of the
durable tables (``KVStore._data``, ``GlobalControlStore._named_actors``)
at mutation time, and ``--restore`` replays the journal over the newest
snapshot. A mutation that bypasses the ``_journal`` hook silently
narrows that guarantee — the write works until the first head restart,
then vanishes. This rule holds the write path statically:

- inside ``ray_tpu/core/gcs.py``: any function that mutates a durable
  table (subscript assign/del, or a mutating method call — pop,
  setdefault, clear, update, popitem) must also call ``_journal(...)``
  in its body, or be named in the ``WAL_EXEMPT_FUNCTIONS`` tuple
  literal (replay/restore internals re-apply already-journaled state;
  journaling them would double-apply every record on the next restore);
- outside gcs.py: no reaching into ``._data`` / ``._named_actors`` of a
  KV/GCS receiver to mutate it — go through ``kv.put``/``kv.delete``/
  ``register_named_actor``/``unregister_named_actor`` so the journal
  hook sees the write.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from .engine import Finding, Project, Rule, SourceFile, register

GCS_MODULE_REL = "ray_tpu/core/gcs.py"

# attributes that ARE the durable tables
_DURABLE_ATTRS = {"_data", "_named_actors"}
# method calls on a table that mutate it
_MUTATING_METHODS = {"pop", "setdefault", "clear", "update", "popitem"}


def exempt_functions(project: Project) -> Set[str]:
    """The WAL_EXEMPT_FUNCTIONS tuple literal in core/gcs.py."""
    out: Set[str] = set()
    sf = project.file(GCS_MODULE_REL)
    if sf is None:
        return out
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "WAL_EXEMPT_FUNCTIONS"
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.add(elt.value)
    return out


def _durable_attr(node: ast.AST) -> Optional[ast.Attribute]:
    """The `<recv>._data` / `<recv>._named_actors` attribute at the root
    of an expression, unwrapping subscripts (`x._data[k]` -> `x._data`)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _DURABLE_ATTRS:
        return node
    return None


def _mutations(tree: ast.AST) -> Iterable[Tuple[int, ast.Attribute]]:
    """(lineno, table_attribute) for every durable-table mutation site:
    subscript assignment, subscript deletion, augmented assignment, and
    mutating method calls."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _durable_attr(target)
                    if attr is not None:
                        yield node.lineno, attr
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Subscript):
                attr = _durable_attr(node.target)
                if attr is not None:
                    yield node.lineno, attr
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript):
                    attr = _durable_attr(target)
                    if attr is not None:
                        yield node.lineno, attr
        elif isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _MUTATING_METHODS):
                attr = _durable_attr(func.value)
                if attr is not None:
                    yield node.lineno, attr


def _calls_journal(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "_journal":
            return True
        if isinstance(func, ast.Name) and func.id == "_journal":
            return True
    return False


def _gcs_receiver(attr: ast.Attribute) -> bool:
    """Whether `<recv>._data` plausibly IS a GCS durable table: the
    receiver chain mentions the kv store or the gcs itself (`self.kv`,
    `gcs.kv`, `store._named_actors`, ...). `_named_actors` is specific
    enough to match on its own; `_data` is a common private name, so
    require a kv/gcs-ish receiver to avoid claiming unrelated caches."""
    if attr.attr == "_named_actors":
        return True
    names: List[str] = []
    node: ast.AST = attr.value
    while isinstance(node, ast.Attribute):
        names.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        names.append(node.id)
    return any(n in ("kv", "gcs", "store", "gcs_store") for n in names)


def module_findings(sf: SourceFile, exempt: Set[str],
                    rule_name: str) -> List[Finding]:
    """gcs.py itself: unjournaled mutating functions."""
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in exempt:
            continue
        sites = [ln for ln, attr in _mutations(node)]
        if not sites:
            continue
        if _calls_journal(node):
            continue
        out.append(Finding(
            rule_name, sf.rel, sites[0],
            f"function {node.name!r} mutates a durable GCS table without "
            f"calling _journal; journal the write or add the function to "
            f"WAL_EXEMPT_FUNCTIONS with a reason"))
    return out


def external_findings(sf: SourceFile, rule_name: str) -> List[Finding]:
    """Outside gcs.py: direct durable-table mutations bypass the WAL."""
    out: List[Finding] = []
    for lineno, attr in _mutations(sf.tree):
        if not _gcs_receiver(attr):
            continue
        out.append(Finding(
            rule_name, sf.rel, lineno,
            f"direct mutation of GCS durable table {attr.attr!r} bypasses "
            f"the WAL; use kv.put/kv.delete or the named-actor registry "
            f"so the write is journaled"))
    return out


@register
class GcsDurableMutationsRule(Rule):
    name = "gcs-durable-mutations"
    doc = ("every mutation of the durable GCS tables (KVStore._data, "
           "named-actor registry) is WAL-journaled: in-module mutators "
           "call _journal or sit in WAL_EXEMPT_FUNCTIONS; nothing "
           "outside core/gcs.py touches the tables directly")

    def check(self, project: Project) -> Iterable[Finding]:
        exempt = exempt_functions(project)
        for sf in project.files_under("ray_tpu/"):
            if sf.rel == GCS_MODULE_REL:
                yield from module_findings(sf, exempt, self.name)
            else:
                yield from external_findings(sf, self.name)
