"""CLI: ``python -m scripts.raylint [options]`` from the repo root.

Exit status is 0 when every finding is fixed, suppressed, or baselined;
1 otherwise. ``--write-baseline`` records the current findings as the
new baseline (preserving existing justifications) and exits 0.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from . import DEFAULT_BASELINE, REGISTRY, Project, run
from .baseline import Baseline
from .reporters import render_json, render_text


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scripts.raylint",
        description="unified static analysis over ray_tpu/",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: the checkout containing this package)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule subset (default: all registered)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable JSON report")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help=f"baseline file (default: {DEFAULT_BASELINE})",
    )
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="record current findings as the new baseline and exit 0",
    )
    parser.add_argument("--show-baselined", action="store_true",
                        help="also list baselined findings in text output")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in sorted(REGISTRY):
            print(f"{name}: {REGISTRY[name].doc}")
        return 0

    root = Path(args.root) if args.root else Path(__file__).resolve().parents[2]
    baseline_path = Path(args.baseline) if args.baseline else root / DEFAULT_BASELINE
    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    project = Project(root)

    t0 = time.monotonic()
    if args.write_baseline:
        result = run(project, rules=rules, baseline=None)
        old = Baseline.load(baseline_path)
        payload = old.write(baseline_path, result.findings, project)
        print(
            f"raylint: baseline written to {baseline_path} "
            f"({len(payload['entries'])} entries; justify any "
            f"TODO entries before committing)"
        )
        return 0

    baseline = None if args.no_baseline else Baseline.load(baseline_path)
    try:
        result = run(project, rules=rules, baseline=baseline)
    except ValueError as exc:  # e.g. an unknown --rules name
        print(f"raylint: {exc}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0
    if args.as_json:
        payload = render_json(result)
        payload["elapsed_s"] = round(elapsed, 3)
        print(json.dumps(payload, indent=2))
    else:
        print(render_text(result, show_baselined=args.show_baselined))
        print(f"raylint: {len(project.files)} files in {elapsed:.2f}s")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
