"""JAX hot-path hygiene: inside functions reachable from jit/shard_map
step definitions in train/, ops/ and parallel/, flag implicit host syncs
and recompilation traps.

Host syncs flagged in hot functions:
- ``float(x)`` on a non-constant — forces a device->host transfer (and a
  blocking sync) when x is a tracer/array;
- zero-arg ``.item()`` — the canonical explicit sync;
- ``np.asarray(...)`` / ``np.array(...)`` on a traced value — silently
  materializes on host;
- ``print(...)`` — printing a tracer syncs (and burns time in the step
  loop); use jax.debug.print.

Recompilation traps (checked in every function of the scoped modules):
- a ``jit``/``jax.jit`` wrapper constructed inside a loop — a fresh
  wrapper per iteration means a fresh trace+compile per iteration;
- ``jit(lambda ...)`` inside a function body — a fresh lambda object per
  call never hits the jit cache.

Reachability is name-level and per-module: decorated jit/shard_map
functions (including ``functools.partial(jax.jit, ...)`` decorators) and
functions passed to ``jit(...)``/``shard_map(...)`` calls are roots; an
intra-module call graph (bare-name and ``self.<name>`` calls) closes
over them.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding, Project, Rule, SourceFile, register

HOT_PATH_PREFIXES = (
    "ray_tpu/train/",
    "ray_tpu/ops/",
    "ray_tpu/parallel/",
    "ray_tpu/serve/llm/",
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_JIT_NAMES = {"jit", "pjit"}
_WRAP_NAMES = {"jit", "pjit", "shard_map"}
_NP_MODULES = {"np", "numpy", "onp"}


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """`jit`, `jax.jit`, `pjit`, `shard_map` as a bare reference."""
    return _tail(node) in _WRAP_NAMES


def _is_jit_call(node: ast.Call) -> bool:
    """A call that produces a compiled wrapper: jit(f), jax.jit(f, ...),
    shard_map(f, mesh=...), functools.partial(jax.jit, ...)."""
    if _is_jit_expr(node.func):
        return True
    if _tail(node.func) == "partial" and node.args:
        return _is_jit_expr(node.args[0])
    return False


def _decorated_as_root(fn) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call) and _is_jit_call(dec):
            return True
        if isinstance(dec, ast.Call) and _is_jit_expr(dec.func):
            return True
    return False


def _collect_functions(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    """name -> FunctionDef nodes (methods and nested defs included; the
    name-level over-approximation errs toward more coverage)."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            out.setdefault(node.name, []).append(node)
    return out


def _called_names(fn: ast.AST) -> Set[str]:
    """Bare-name and self-method call targets, plus any function NAME
    passed as an argument to another call (step functions ride into
    helpers as values: make_step(loss_fn))."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            t = _tail(node.func)
            if t:
                names.add(t)
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
    return names


def hot_roots(tree: ast.AST) -> Set[str]:
    """Function names that are jit/shard_map entry points."""
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES) and _decorated_as_root(node):
            roots.add(node.name)
        if isinstance(node, ast.Call) and _is_jit_call(node):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    roots.add(arg.id)
                elif isinstance(arg, ast.Attribute):
                    roots.add(arg.attr)
    return roots


def reachable_hot_functions(
    trees,
) -> Dict[int, Tuple[str, str, ast.AST]]:
    """id(def node) -> (rel, name, def node) for every function reachable
    from a hot root through the name-level call graph. `trees` is
    [(rel, ast)] — the graph spans ALL of them jointly, because jitted
    steps in train/ call loss/attention helpers defined in ops/."""
    if isinstance(trees, ast.AST):  # single-module convenience
        trees = [("", trees)]
    functions: Dict[str, List[Tuple[str, ast.AST]]] = {}
    roots: Set[str] = set()
    for rel, tree in trees:
        for name, defs in _collect_functions(tree).items():
            functions.setdefault(name, []).extend(
                (rel, fn) for fn in defs
            )
        roots.update(hot_roots(tree))
    frontier = [n for n in roots if n in functions]
    reached: Set[str] = set()
    while frontier:
        name = frontier.pop()
        if name in reached:
            continue
        reached.add(name)
        for _, fn in functions[name]:
            for callee in _called_names(fn):
                if callee in functions and callee not in reached:
                    frontier.append(callee)
    return {
        id(fn): (rel, name, fn)
        for name in reached
        for rel, fn in functions[name]
    }


def _touches_shape(node: ast.AST) -> bool:
    """float(x.shape[0] * ...) operates on static Python ints, not
    device values — never a sync."""
    return any(
        isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                        "size", "dtype")
        for sub in ast.walk(node)
    )


def _host_sync_reason(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Name):
        if (
            func.id == "float"
            and node.args
            and not isinstance(node.args[0], ast.Constant)
            and not _touches_shape(node.args[0])
        ):
            return ("float(...) forces a device->host sync on a traced "
                    "value; keep it as an array (or jnp.float32(...))")
        if func.id == "print":
            return ("print(...) inside a jit hot path syncs tracers to "
                    "host; use jax.debug.print")
        return None
    if isinstance(func, ast.Attribute):
        if func.attr == "item" and not node.args and not node.keywords:
            return (".item() is an explicit host sync; hot paths must "
                    "stay on device")
        if (
            func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in _NP_MODULES
        ):
            return (f"np.{func.attr}(...) materializes a traced value on "
                    f"host; use jnp.{func.attr}")
    return None


def _in_function_body(fn: ast.AST):
    """Walk fn's body without descending into nested function scopes."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FUNC_NODES + (ast.Lambda,)):
            stack.extend(ast.iter_child_nodes(node))


def hot_sync_findings(
    hot: Dict[int, Tuple[str, str, ast.AST]]
) -> List[Finding]:
    """Host-sync findings over a joint reachable-function map."""
    findings: List[Finding] = []
    flagged: Set[Tuple[str, int, str]] = set()
    for rel, name, fn in sorted(
        hot.values(), key=lambda t: (t[0], t[2].lineno)
    ):
        for node in _in_function_body(fn):
            if not isinstance(node, ast.Call):
                continue
            reason = _host_sync_reason(node)
            if reason is None:
                continue
            key = (rel, node.lineno, reason)
            if key in flagged:
                continue  # one finding per site even if multiply reachable
            flagged.add(key)
            findings.append(Finding(
                "jax-hot-path", rel, node.lineno,
                f"in {name}() (reachable from a jit/shard_map step): "
                f"{reason}",
            ))
    return findings


_STEP_CALL = re.compile(r"(^|_)step(_fn)?$")


def step_loop_findings(sf: SourceFile) -> List[Finding]:
    """Host syncs inside a step-DISPATCH loop: a For/While whose body
    calls a `*step`/`*step_fn` wrapper. Syncing there (float()/.item()/
    np.asarray on the step's outputs) blocks jax's async dispatch every
    iteration — the device idles while the host converts metrics."""
    findings: List[Finding] = []

    def is_step_loop(loop: ast.AST) -> Optional[str]:
        for node in ast.walk(loop):
            if isinstance(node, ast.Call):
                t = _tail(node.func)
                if t and _STEP_CALL.search(t):
                    return t
        return None

    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            continue
        step_name = is_step_loop(node)
        if step_name is None:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            reason = _host_sync_reason(sub)
            if reason is not None:
                findings.append(Finding(
                    "jax-hot-path", sf.rel, sub.lineno,
                    f"in the step-dispatch loop (calls {step_name}()): "
                    f"{reason} — syncing every iteration stalls jax "
                    f"async dispatch",
                ))
    return findings


def recompile_trap_findings(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []

    # recompilation traps: jit wrappers built in loops / jit(lambda)
    def walk(node: ast.AST, in_loop: bool, in_fn: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(
                node, (ast.For, ast.While, ast.AsyncFor)
            )
            child_in_fn = in_fn or isinstance(node, _FUNC_NODES)
            if isinstance(child, ast.Call) and _is_jit_call(child):
                if child_in_loop:
                    findings.append(Finding(
                        "jax-hot-path", sf.rel, child.lineno,
                        "jit/shard_map wrapper constructed inside a loop — "
                        "every iteration re-traces and recompiles; hoist "
                        "the wrapper out of the loop",
                    ))
                elif child_in_fn and child.args and isinstance(
                    child.args[0], ast.Lambda
                ):
                    findings.append(Finding(
                        "jax-hot-path", sf.rel, child.lineno,
                        "jit(lambda ...) inside a function body — a fresh "
                        "lambda per call never hits the jit cache and "
                        "recompiles every call; define the function once",
                    ))
            walk(child, child_in_loop, child_in_fn)

    walk(sf.tree, False, False)
    return findings


@register
class JaxHotPathRule(Rule):
    name = "jax-hot-path"
    doc = ("Functions reachable from jit/shard_map step definitions in "
           "train/, ops/ and parallel/ must not host-sync (float()/"
           ".item()/np.asarray/print on tracers) or rebuild jit wrappers "
           "per call/iteration.")

    def check(self, project: Project) -> Iterable[Finding]:
        scoped = project.files_under(*HOT_PATH_PREFIXES)
        hot = reachable_hot_functions([(sf.rel, sf.tree) for sf in scoped])
        yield from hot_sync_findings(hot)
        for sf in scoped:
            yield from step_loop_findings(sf)
            yield from recompile_trap_findings(sf)
