"""event-kinds: every flight-recorder emit names a registered kind.

The cluster event plane (ray_tpu/util/events.py) is TYPED: consumers —
the postmortem reconstructor, the goodput accountant, `ray_tpu events
--kind` — key off the ``kind`` field, so an emit without one (or with a
typo'd one) silently drops out of every downstream view. This rule
holds every ``emit(...)`` / ``events().emit(...)`` call site under
``ray_tpu/`` to the registry:

- the call must pass ``kind=`` ;
- the value must be a string literal (dynamic kinds defeat static
  checking — build the registry entry instead);
- the literal must be registered: a key of the ``EVENT_KINDS`` dict
  literal in util/events.py, or the first argument of any
  ``register_event_kind("...")`` call in the tree.

``ray_tpu/util/events.py`` itself is exempt (it defines the plumbing
that forwards ``kind`` through). Call sites with a legitimate reason to
bypass the registry belong in the baseline with a justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import Finding, Project, Rule, SourceFile, register

EVENTS_MODULE_REL = "ray_tpu/util/events.py"


def registered_kinds(project: Project) -> Set[str]:
    """The static kind registry: EVENT_KINDS literal keys plus every
    register_event_kind("...") string-literal call in the tree."""
    kinds: Set[str] = set()
    events_sf = project.file(EVENTS_MODULE_REL)
    if events_sf is not None:
        for node in ast.walk(events_sf.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):  # EVENT_KINDS: Dict[...] = {}
                targets = [node.target]
            else:
                continue
            if (any(isinstance(t, ast.Name) and t.id == "EVENT_KINDS"
                    for t in targets)
                    and isinstance(node.value, ast.Dict)):
                for key in node.value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        kinds.add(key.value)
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "register_event_kind"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                kinds.add(node.args[0].value)
    return kinds


def _emit_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to util.events' emit via `from ... import`."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        module = node.module or ""
        if not (module == "events" or module.endswith(".events")
                or module == "util.events"):
            continue
        for alias in node.names:
            if alias.name == "emit":
                aliases.add(alias.asname or alias.name)
    return aliases


def _is_events_factory_call(func: ast.AST) -> bool:
    """True for `events().emit` / `<x>.events().emit` receivers."""
    return (isinstance(func, ast.Call)
            and ((isinstance(func.func, ast.Name)
                  and func.func.id == "events")
                 or (isinstance(func.func, ast.Attribute)
                     and func.func.attr == "events")))


def emit_call_findings(sf: SourceFile, kinds: Set[str],
                       rule_name: str = "event-kinds") -> List[Finding]:
    aliases = _emit_aliases(sf.tree)
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_emit = (isinstance(func, ast.Name) and func.id in aliases) or (
            isinstance(func, ast.Attribute) and func.attr == "emit"
            and _is_events_factory_call(func.value)
        )
        if not is_emit:
            continue
        msg = _check_kind_kwarg(node, kinds)
        if msg is not None:
            out.append(Finding(rule_name, sf.rel, node.lineno, msg))
    return out


def _check_kind_kwarg(call: ast.Call, kinds: Set[str]) -> Optional[str]:
    kind_kw = next((kw for kw in call.keywords if kw.arg == "kind"), None)
    if kind_kw is None:
        # positional kind (4th positional arg of emit) counts too
        if len(call.args) >= 4:
            kind_kw = ast.keyword(arg="kind", value=call.args[3])
        else:
            return ("events.emit without kind=: pass a registered event "
                    "kind (see EVENT_KINDS in util/events.py)")
    if not (isinstance(kind_kw.value, ast.Constant)
            and isinstance(kind_kw.value.value, str)):
        return ("events.emit kind= must be a string literal so the "
                "registry check stays static")
    kind = kind_kw.value.value
    if kind not in kinds:
        return (f"events.emit kind={kind!r} is not registered in "
                f"EVENT_KINDS (util/events.py) or via register_event_kind")
    return None


@register
class EventKindsRule(Rule):
    name = "event-kinds"
    doc = ("every events.emit call site in ray_tpu/ passes a kind= "
           "string literal registered in the event schema")

    def check(self, project: Project) -> Iterable[Finding]:
        kinds = registered_kinds(project)
        for sf in project.files_under("ray_tpu/"):
            if sf.rel == EVENTS_MODULE_REL:
                continue  # the plumbing that forwards kind through
            yield from emit_call_findings(sf, kinds, self.name)
