"""Committed baseline of pre-existing findings.

Each entry pins one finding by a line-number-insensitive fingerprint
(rule + path + the offending source line's stripped text + its occurrence
index among identical lines), so unrelated edits above a finding don't
invalidate the baseline. Entries carry a human `justification` — a
baselined finding is an explicit engineering decision, not a mute button.

Regenerate with ``python -m scripts.raylint --write-baseline``; existing
justifications are preserved for entries that persist, new entries get a
TODO placeholder that should be replaced before committing.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .engine import Finding, Project

VERSION = 1
TODO_JUSTIFICATION = "TODO: justify or fix this finding"


def _fingerprint(rule: str, path: str, text: str, occurrence: int) -> str:
    blob = f"{rule}|{path}|{text}|{occurrence}".encode()
    return hashlib.sha1(blob).hexdigest()[:16]


def _line_text(project: Project, finding: Finding) -> str:
    sf = project.file(finding.path)
    if sf is not None and 1 <= finding.line <= len(sf.lines):
        return sf.lines[finding.line - 1].strip()
    return finding.message  # project-scope findings without a source line


def fingerprints(findings: List[Finding],
                 project: Project) -> List[Tuple[Finding, str]]:
    """Pair each finding with its fingerprint, disambiguating identical
    (rule, path, line-text) triples by order of appearance."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, _line_text(project, f))
        occurrence = seen.get(key, 0)
        seen[key] = occurrence + 1
        out.append((f, _fingerprint(*key, occurrence)))
    return out


class Baseline:
    """Load/apply/write the committed findings baseline."""

    def __init__(self, entries: List[dict], path: Optional[Path] = None):
        self.entries = entries
        self.path = path

    @classmethod
    def empty(cls) -> "Baseline":
        return cls([])

    @classmethod
    def load(cls, path) -> "Baseline":
        path = Path(path)
        if not path.exists():
            return cls([], path)
        data = json.loads(path.read_text())
        return cls(list(data.get("entries", [])), path)

    def apply(self, findings: List[Finding], project: Project):
        """Split findings into (actionable, baselined); also return the
        stale baseline entries that matched nothing (fixed or moved —
        prune them with --write-baseline)."""
        budget: Dict[str, int] = {}
        for entry in self.entries:
            fp = entry.get("fingerprint", "")
            budget[fp] = budget.get(fp, 0) + 1
        actionable, baselined = [], []
        for finding, fp in fingerprints(findings, project):
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                baselined.append(finding)
            else:
                actionable.append(finding)
        stale = []
        remaining = dict(budget)  # unmatched counts after consumption
        for entry in self.entries:
            fp = entry.get("fingerprint", "")
            if remaining.get(fp, 0) > 0:
                remaining[fp] -= 1
                stale.append(entry)
        return actionable, baselined, stale

    def write(self, path, findings: List[Finding], project: Project) -> dict:
        """Write a fresh baseline covering `findings`, preserving the
        justification of any entry whose fingerprint persists."""
        path = Path(path)
        old_just = {
            e.get("fingerprint"): e.get("justification")
            for e in self.entries
            if e.get("justification")
            and e.get("justification") != TODO_JUSTIFICATION
        }
        entries = []
        for finding, fp in fingerprints(findings, project):
            entries.append({
                "rule": finding.rule,
                "path": finding.path,
                "line": finding.line,
                "text": _line_text(project, finding),
                "fingerprint": fp,
                "justification": old_just.get(fp, TODO_JUSTIFICATION),
            })
        payload = {"version": VERSION, "entries": entries}
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(payload, indent=2) + "\n")
        tmp.replace(path)
        return payload
