"""raylint core: parsed-file cache, rule registry, suppression handling.

One ``ast.parse`` per file feeds every rule (the whole-repo run must fit
the tier-1 time budget). Findings are repo-root-relative so the baseline
stays stable across checkouts.

Suppression syntax:

- ``# raylint: disable=<rule>[,<rule>...]`` on the offending line
  silences those rules for that line (``all`` silences every rule).
- ``# raylint: disable-file=<rule>[,<rule>...]`` anywhere in a file
  silences those rules for the whole file.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

__all__ = [
    "Finding", "Project", "Rule", "RunResult", "SourceFile",
    "REGISTRY", "register", "run",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: rule name, repo-relative path, 1-based line."""

    rule: str
    path: str
    line: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"


_SUPPRESS_LINE = re.compile(r"#\s*raylint:\s*disable=([\w\-, ]+)")
_SUPPRESS_FILE = re.compile(r"#\s*raylint:\s*disable-file=([\w\-, ]+)")


class SourceFile:
    """One file under analysis: text, split lines, lazily parsed AST and
    suppression table, all computed once and shared across rules."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.root = root
        try:
            self.rel = path.relative_to(root).as_posix()
        except ValueError:
            self.rel = path.as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._suppressions: Optional[Dict[int, Set[str]]] = None

    @property
    def tree(self) -> ast.AST:
        if self._tree is None:
            self._tree = ast.parse(self.text)
        return self._tree

    @property
    def suppressions(self) -> Dict[int, Set[str]]:
        """line -> suppressed rule names; key 0 covers the whole file."""
        if self._suppressions is None:
            table: Dict[int, Set[str]] = {}
            for lineno, line in enumerate(self.lines, 1):
                m = _SUPPRESS_FILE.search(line)
                if m:
                    table.setdefault(0, set()).update(
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    )
                    continue
                m = _SUPPRESS_LINE.search(line)
                if m:
                    table.setdefault(lineno, set()).update(
                        r.strip() for r in m.group(1).split(",") if r.strip()
                    )
            self._suppressions = table
        return self._suppressions

    def suppressed(self, rule: str, line: int) -> bool:
        for scope in (0, line):
            rules = self.suppressions.get(scope)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


class Project:
    """The tree under analysis: repo root, the package, and the extra
    top-level entry points the kernel-fallback rule also covers."""

    def __init__(self, root, package: str = "ray_tpu",
                 extra_files: Sequence[str] = ("bench.py", "bench_serve.py")):
        self.root = Path(root).resolve()
        self.package_root = self.root / package
        paths: List[Path] = []
        if self.package_root.exists():
            paths.extend(sorted(self.package_root.rglob("*.py")))
        for name in extra_files:
            p = self.root / name
            if p.exists():
                paths.append(p)
        self.files: List[SourceFile] = [SourceFile(p, self.root) for p in paths]
        self._by_rel = {sf.rel: sf for sf in self.files}

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def files_under(self, *rel_prefixes: str) -> List[SourceFile]:
        return [
            sf for sf in self.files
            if any(sf.rel.startswith(p) for p in rel_prefixes)
        ]


class Rule:
    """A registered analysis pass. Subclasses set `name`/`doc` and yield
    Findings from check(); suppression and baselining are applied by the
    engine afterwards."""

    name: str = ""
    doc: str = ""

    def check(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError


REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    rule = cls()
    assert rule.name, f"{cls.__name__} has no rule name"
    REGISTRY[rule.name] = rule
    return cls


@dataclasses.dataclass
class RunResult:
    findings: List[Finding]           # actionable (neither suppressed nor baselined)
    baselined: List[Finding]
    suppressed: int
    stale_baseline: List[dict]        # baseline entries that no longer match
    counts: Dict[str, int]            # actionable findings per ran rule (0s included)
    ran_rules: List[str]

    @property
    def ok(self) -> bool:
        return not self.findings


def run(project_or_root, rules: Optional[Sequence[str]] = None,
        baseline=None) -> RunResult:
    """Run `rules` (default: all registered) over the project; apply
    suppression comments, then the baseline. `baseline` is a
    baseline.Baseline or None."""
    project = (
        project_or_root if isinstance(project_or_root, Project)
        else Project(project_or_root)
    )
    names = list(rules) if rules else sorted(REGISTRY)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise ValueError(
            f"unknown rule(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(REGISTRY))})"
        )
    raw: List[Finding] = []
    for name in names:
        raw.extend(REGISTRY[name].check(project))
    kept: List[Finding] = []
    suppressed = 0
    for f in raw:
        sf = project.file(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    if baseline is not None:
        actionable, baselined, stale = baseline.apply(kept, project)
    else:
        actionable, baselined, stale = kept, [], []
    counts = {name: 0 for name in names}
    for f in actionable:
        counts[f.rule] += 1
    return RunResult(
        findings=actionable,
        baselined=baselined,
        suppressed=suppressed,
        stale_baseline=stale,
        counts=counts,
        ran_rules=names,
    )
