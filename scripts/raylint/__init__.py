"""raylint: unified AST static analysis for ray_tpu.

One engine (parsed-file cache, rule registry, `# raylint:` suppression
comments, committed baseline, text/JSON reporters) carrying:

- the five legacy checks as rules: typed-errors, metrics-names,
  atomic-writes, lazy-jax, kernel-fallbacks (the old scripts/check_*.py
  entry points are thin shims over these);
- lock-discipline: `# guarded-by:` annotated attributes only accessed
  under their lock; lock-order: no acquisition-order cycles;
- blocking-under-lock: no sleeps/joins/waits/RPCs inside a critical
  section;
- jax-hot-path: no host syncs or recompilation traps in functions
  reachable from jit/shard_map step definitions;
- event-kinds: every events.emit call site passes a kind registered in
  the flight-recorder event schema (util/events.py EVENT_KINDS);
- request-phase: every reqlog.mark call site passes a phase registered
  in the request-forensics schema (serve/reqlog.py PHASES);
- step-phase: every steplog.mark call site passes a phase registered in
  the training-forensics schema (train/steplog.py STEP_PHASES);
- gcs-durable-mutations: every durable GCS table write is WAL-journaled
  (core/gcs.py _journal hook or WAL_EXEMPT_FUNCTIONS; no direct table
  mutation outside gcs.py).

Run ``python -m scripts.raylint`` from the repo root; see README
"Static analysis".
"""

from .engine import (  # noqa: F401
    REGISTRY,
    Finding,
    Project,
    Rule,
    RunResult,
    SourceFile,
    register,
    run,
)

# importing the rule modules populates REGISTRY
from . import rules_legacy  # noqa: F401,E402
from . import rules_locks  # noqa: F401,E402
from . import rules_jax  # noqa: F401,E402
from . import rules_events  # noqa: F401,E402
from . import rules_requests  # noqa: F401,E402
from . import rules_steps  # noqa: F401,E402
from . import rules_gcs  # noqa: F401,E402

DEFAULT_BASELINE = "scripts/raylint/baseline.json"
