#!/usr/bin/env python
"""Thin compatibility shim over scripts/raylint (rule: metrics-names).

The logic lives in scripts/raylint/rules_legacy.py; this entry point
keeps the historical CLI (`python scripts/check_metrics_names.py
[package_root]`) and module API (check) for existing tier-1 wiring.
Repo-wide enforcement runs through `python -m scripts.raylint`
(tests/test_raylint.py).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from scripts.raylint.rules_legacy import check  # noqa: E402,F401 - compat API


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else _REPO / "ray_tpu"
    errors = check(root)
    for err in errors:
        print(f"check_metrics_names: {err}", file=sys.stderr)
    if errors:
        print(f"check_metrics_names: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_metrics_names: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
