#!/usr/bin/env python
"""Static check: metric naming + registration discipline in ray_tpu/.

Two rules, enforced over every literal-name Counter(/Gauge(/Histogram(
instantiation (including the get_or_create_* accessors) in the package:

1. Every metric name carries the ``raytpu_`` prefix — the scrape
   namespace stays collision-free against other exporters.
2. A literal name may be DIRECTLY constructed (bare ``Counter("x"``,
   not ``get_or_create_counter("x"``) at most once across the package:
   a second direct construction would shadow the registered series with
   a fresh zeroed one (MetricsRegistry.register overwrites). Re-runnable
   emitters must go through get_or_create_*.

Exits non-zero listing violations; run by tier-1 via
tests/test_observability.py.
"""

from __future__ import annotations

import re
import sys
from collections import defaultdict
from pathlib import Path

# literal-first-arg metric instantiations; group 1 = constructor,
# group 2 = metric name
_PATTERN = re.compile(
    r"""(?<![\w.])(Counter|Gauge|Histogram|
        get_or_create_counter|get_or_create_gauge|get_or_create_histogram)
        \(\s*["']([^"']+)["']""",
    re.VERBOSE,
)
_DIRECT = {"Counter", "Gauge", "Histogram"}


def check(package_root: Path):
    errors = []
    direct_sites = defaultdict(list)  # metric name -> [file:line]
    for path in sorted(package_root.rglob("*.py")):
        text = path.read_text()
        for lineno, line in enumerate(text.splitlines(), 1):
            stripped = line.strip()
            if stripped.startswith(("class ", "def ", "#")):
                continue
            for match in _PATTERN.finditer(line):
                ctor, name = match.group(1), match.group(2)
                site = f"{path.relative_to(package_root.parent)}:{lineno}"
                if not name.startswith("raytpu_"):
                    errors.append(
                        f"{site}: metric {name!r} missing the raytpu_ prefix"
                    )
                if ctor in _DIRECT:
                    direct_sites[name].append(site)
    for name, sites in sorted(direct_sites.items()):
        if len(sites) > 1:
            errors.append(
                f"metric {name!r} directly constructed at {len(sites)} sites "
                f"({', '.join(sites)}): all but the first silently shadow the "
                f"registered series — use get_or_create_*"
            )
    return errors


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else (
        Path(__file__).resolve().parent.parent / "ray_tpu"
    )
    errors = check(root)
    for err in errors:
        print(f"check_metrics_names: {err}", file=sys.stderr)
    if errors:
        print(f"check_metrics_names: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_metrics_names: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
