#!/usr/bin/env python
"""Static check: metric naming + registration discipline in ray_tpu/.

Four rules, enforced over every literal-name Counter(/Gauge(/Histogram(
instantiation (including the get_or_create_* accessors) in the package:

1. Every metric name carries the ``raytpu_`` prefix — the scrape
   namespace stays collision-free against other exporters.
2. A literal name may be DIRECTLY constructed (bare ``Counter("x"``,
   not ``get_or_create_counter("x"``) at most once across the package:
   a second direct construction would shadow the registered series with
   a fresh zeroed one (MetricsRegistry.register overwrites). Re-runnable
   emitters must go through get_or_create_*.
3. Every histogram registration passes explicit ``boundaries=``: the
   constructor's fallback buckets silently misfit most latency
   distributions, and two call sites disagreeing about the default
   would fork the series shape.
4. Gauge sampler callbacks run ONLY through Gauge.collect's
   sampler-failure guard: calling a metric's ``._fn(`` directly, or
   overriding ``collect()`` outside util/metrics.py, bypasses the guard
   and lets one broken sampler kill the whole scrape.

Exits non-zero listing violations; run by tier-1 via
tests/test_observability.py.
"""

from __future__ import annotations

import re
import sys
from collections import defaultdict
from pathlib import Path

# literal-first-arg metric instantiations; group 1 = constructor,
# group 2 = metric name
_PATTERN = re.compile(
    r"""(?<![\w.])(Counter|Gauge|Histogram|
        get_or_create_counter|get_or_create_gauge|get_or_create_histogram)
        \(\s*["']([^"']+)["']""",
    re.VERBOSE,
)
_DIRECT = {"Counter", "Gauge", "Histogram"}
_HISTOGRAMS = {"Histogram", "get_or_create_histogram"}
# the one module allowed to touch sampler internals (it IS the guard)
_GUARD_MODULE = "metrics.py"


def _call_text(text: str, start: int, limit: int = 4000) -> str:
    """The full call expression from the opening paren at/after `start`
    to its balanced close (string-naive: metric registrations never
    embed unbalanced parens in literals)."""
    i = text.index("(", start)
    depth = 0
    for j in range(i, min(len(text), i + limit)):
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                return text[i:j + 1]
    return text[i:i + limit]


def check(package_root: Path):
    errors = []
    direct_sites = defaultdict(list)  # metric name -> [file:line]
    for path in sorted(package_root.rglob("*.py")):
        text = path.read_text()
        lines = text.splitlines()
        rel = path.relative_to(package_root.parent)
        for match in _PATTERN.finditer(text):
            lineno = text.count("\n", 0, match.start()) + 1
            line = lines[lineno - 1].strip()
            if line.startswith(("class ", "def ", "#")):
                continue
            ctor, name = match.group(1), match.group(2)
            site = f"{rel}:{lineno}"
            if not name.startswith("raytpu_"):
                errors.append(
                    f"{site}: metric {name!r} missing the raytpu_ prefix"
                )
            if ctor in _DIRECT:
                direct_sites[name].append(site)
            if ctor in _HISTOGRAMS:
                call = _call_text(text, match.start())
                if "boundaries" not in call:
                    errors.append(
                        f"{site}: histogram {name!r} registered without "
                        f"explicit boundaries= — the default buckets misfit "
                        f"most latency distributions"
                    )
        # rule 4: sampler-guard bypasses (outside the guard module)
        if path.name == _GUARD_MODULE and path.parent.name == "util":
            continue
        for lineno, line in enumerate(lines, 1):
            stripped = line.strip()
            if stripped.startswith("#"):
                continue
            if re.search(r"\._fn\(\s*\)", line):
                # samplers are zero-arg callables; `obj._fn(args)` is
                # some other attribute, not a gauge callback
                errors.append(
                    f"{rel}:{lineno}: direct sampler call `._fn()` bypasses "
                    f"the Gauge.collect sampler-failure guard — sample "
                    f"through collect()/prometheus_text()"
                )
            if re.match(r"\s*def collect\(", line):
                errors.append(
                    f"{rel}:{lineno}: collect() override outside "
                    f"util/metrics.py — callback gauges must go through the "
                    f"guarded Gauge.collect, not reimplement it"
                )
    for name, sites in sorted(direct_sites.items()):
        if len(sites) > 1:
            errors.append(
                f"metric {name!r} directly constructed at {len(sites)} sites "
                f"({', '.join(sites)}): all but the first silently shadow the "
                f"registered series — use get_or_create_*"
            )
    return errors


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else (
        Path(__file__).resolve().parent.parent / "ray_tpu"
    )
    errors = check(root)
    for err in errors:
        print(f"check_metrics_names: {err}", file=sys.stderr)
    if errors:
        print(f"check_metrics_names: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_metrics_names: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
