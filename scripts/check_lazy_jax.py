#!/usr/bin/env python
"""Static check: jax imports must stay FUNCTION-LOCAL in the modules
that observer/agent processes import without an accelerator stack.

``util/profiling.py``, ``core/stats.py``, and ``util/tracing.py`` are
imported by every runtime init, by the node stats heartbeat, and by the
CLI observer paths (`ray_tpu status --address ...` on a laptop). A
module-level ``import jax`` there would (a) make jax-less hosts unable
to import the package's observability surface at all and (b) force the
multi-second jax import onto processes that only want to LIST profiles,
not take them. The contract: these modules import jax lazily inside the
functions that actually touch the device (or probe ``sys.modules`` to
skip the work when jax was never imported).

Rule: no ``import jax`` / ``from jax ... import`` outside a function
body in the checked modules (class bodies and module scope both count
as violations; ``if TYPE_CHECKING:`` blocks are exempt).

Exits non-zero listing violations; run by tier-1 via
tests/test_profiling.py (next to check_metrics_names.py et al.).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

CHECKED = (
    Path("ray_tpu") / "util" / "profiling.py",
    Path("ray_tpu") / "core" / "stats.py",
    Path("ray_tpu") / "util" / "tracing.py",
)


def _is_jax_import(node: ast.AST) -> bool:
    if isinstance(node, ast.Import):
        return any(
            alias.name == "jax" or alias.name.startswith("jax.")
            for alias in node.names
        )
    if isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        return mod == "jax" or mod.startswith("jax.")
    return False


def _walk(node: ast.AST, in_function: bool, in_type_checking: bool, out):
    for child in ast.iter_child_nodes(node):
        child_in_fn = in_function or isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        )
        child_tc = in_type_checking or (
            isinstance(node, ast.If)
            and isinstance(node.test, (ast.Name, ast.Attribute))
            and "TYPE_CHECKING" in ast.dump(node.test)
        )
        if _is_jax_import(child) and not child_in_fn and not child_tc:
            out.append(child.lineno)
        _walk(child, child_in_fn, child_tc, out)


def check_file(path: Path):
    tree = ast.parse(path.read_text())
    offenders: list = []
    _walk(tree, in_function=False, in_type_checking=False, out=offenders)
    return [
        f"{path}:{lineno}: module-level jax import — move it inside the "
        f"function that needs it (this module must import on jax-less hosts)"
        for lineno in offenders
    ]


def main() -> int:
    repo = Path(__file__).resolve().parent.parent
    errors = []
    for rel in CHECKED:
        path = repo / rel
        if not path.exists():
            errors.append(f"{path}: checked module is missing")
            continue
        errors.extend(check_file(path))
    if errors:
        print("\n".join(errors))
        return 1
    print(f"check_lazy_jax: {len(CHECKED)} modules clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
