#!/usr/bin/env python
"""Thin compatibility shim over scripts/raylint (rule: lazy-jax).

The logic lives in scripts/raylint/rules_legacy.py; this entry point
keeps the historical CLI (`python scripts/check_lazy_jax.py`) for
existing tier-1 wiring. Repo-wide enforcement runs through
`python -m scripts.raylint` (tests/test_raylint.py).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from scripts.raylint import Project, run  # noqa: E402
from scripts.raylint.rules_legacy import (  # noqa: E402,F401 - compat API
    LAZY_JAX_MODULES,
    module_level_jax_imports,
)


def main() -> int:
    result = run(Project(_REPO), rules=["lazy-jax"])
    for f in result.findings:
        print(f"{f.location}: {f.message}")
    if result.findings:
        return 1
    print(f"check_lazy_jax: {len(LAZY_JAX_MODULES)} modules clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
