#!/usr/bin/env python
"""Thin compatibility shim over scripts/raylint (rule: kernel-fallbacks).

The logic lives in scripts/raylint/rules_legacy.py; this entry point
keeps the historical CLI (`python scripts/check_kernel_fallbacks.py`)
for existing tier-1 wiring. Repo-wide enforcement runs through
`python -m scripts.raylint` (tests/test_raylint.py).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from scripts.raylint import Project, run  # noqa: E402
from scripts.raylint.rules_legacy import (  # noqa: E402,F401 - compat API
    REQUIRED_FLAGS,
    cfg_reads,
    defined_flags,
)


def main() -> int:
    project = Project(_REPO)
    result = run(project, rules=["kernel-fallbacks"])
    for f in result.findings:
        print(f"{f.location}: {f.message}")
    if result.findings:
        return 1
    config = project.file("ray_tpu/core/config.py")
    flags = defined_flags(config.tree) if config else set()
    print(
        f"check_kernel_fallbacks: ok ({len(flags)} registered flags, "
        f"all cfg reads resolve, pltpu kernels keep fallbacks)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
