#!/usr/bin/env python
"""Static check: TPU-gated kernels keep non-TPU fallbacks, config knobs
stay registered.

Two invariants the kernel/collectives work of round 6 depends on:

1. **Kernel fallbacks.** Any module under ``ray_tpu/`` that uses
   ``pltpu`` (the Mosaic TPU pallas extension) must stay importable and
   runnable on CPU-only hosts: the ``pltpu`` import has to be guarded by
   try/except ImportError, and the module must carry a non-TPU execution
   path — either a ``*reference*`` XLA implementation or an
   ``interpret=``-driven pallas call. Tier-1 runs on CPU; an unguarded
   TPU-only kernel would pass review and break every non-TPU user.

2. **Config knobs.** Every ``cfg.<name>`` attribute read anywhere in the
   tree must correspond to a ``define_flag(...)`` registration in
   ``core/config.py`` (the one place flags are documented and
   env-overridable). A typo'd or unregistered knob raises only at
   runtime on the path that reads it; this catches it statically. The
   round-6 knobs (attn_pipeline, dp_allreduce_dtype, dp_shard_update,
   dp_quant_block) are additionally pinned by name.

Exits non-zero listing violations; wired into tier-1 via
tests/test_ops.py (next to check_lazy_jax.py et al.).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REQUIRED_FLAGS = (
    "attn_pipeline",
    "dp_allreduce_dtype",
    "dp_shard_update",
    "dp_quant_block",
)

# RayTpuConfig API that is not a flag read
_CFG_METHODS = {"set", "reset", "describe", "as_dict"}


def _uses_pltpu(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id == "pltpu":
            return True
    return False


def _pltpu_import_guarded(tree: ast.AST) -> bool:
    """The `from jax.experimental.pallas import tpu as pltpu` import must
    sit inside a try/except ImportError (or be function-local)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            handled = any(
                isinstance(h.type, ast.Name)
                and h.type.id in ("ImportError", "Exception")
                or isinstance(h.type, ast.Tuple)
                for h in node.handlers
            )
            if not handled:
                continue
            for child in ast.walk(node):
                if isinstance(child, ast.ImportFrom):
                    mod = child.module or ""
                    if mod.startswith("jax.experimental.pallas") and any(
                        a.asname == "pltpu" or a.name == "tpu"
                        for a in child.names
                    ):
                        return True
    return False


def _has_fallback_path(tree: ast.AST) -> bool:
    """A `*reference*` function (pure-XLA ground truth) or an
    `interpret=` kwarg on some call (interpret-mode driver)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if "reference" in node.name:
                return True
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "interpret":
                    return True
        if isinstance(node, ast.arg) and node.arg == "interpret":
            return True
    return False


def _defined_flags(config_path: Path) -> set:
    tree = ast.parse(config_path.read_text())
    flags = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "define_flag"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            flags.add(node.args[0].value)
    return flags


def _cfg_reads(path: Path):
    """(lineno, attr) for attribute reads on `cfg` — only in modules that
    import cfg from the config registry and never rebind the name."""
    tree = ast.parse(path.read_text())
    imports_cfg = any(
        isinstance(node, ast.ImportFrom)
        and (node.module or "").endswith("config")
        and any(a.name == "cfg" for a in node.names)
        for node in ast.walk(tree)
    )
    if not imports_cfg:
        return []
    for node in ast.walk(tree):  # local rebinding shadows the registry
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "cfg":
                    return []
    return [
        (node.lineno, node.attr)
        for node in ast.walk(tree)
        if isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "cfg"
    ]


def main() -> int:
    repo = Path(__file__).resolve().parent.parent
    errors = []

    config_path = repo / "ray_tpu" / "core" / "config.py"
    flags = _defined_flags(config_path)
    for name in REQUIRED_FLAGS:
        if name not in flags:
            errors.append(
                f"{config_path}: required flag {name!r} is not registered "
                "via define_flag"
            )

    py_files = sorted(
        list((repo / "ray_tpu").rglob("*.py"))
        + [repo / "bench.py", repo / "bench_serve.py"]
    )
    kernel_modules = []
    for path in py_files:
        tree = ast.parse(path.read_text())
        if _uses_pltpu(tree):
            kernel_modules.append(path)
            if not _pltpu_import_guarded(tree):
                errors.append(
                    f"{path}: pltpu import is not guarded by try/except "
                    "ImportError — non-TPU builds must still import this"
                )
            if not _has_fallback_path(tree):
                errors.append(
                    f"{path}: pltpu-gated kernels but no registered non-TPU "
                    "fallback (need a *reference* function or an "
                    "interpret= driver)"
                )
        for lineno, attr in _cfg_reads(path):
            if attr not in flags and attr not in _CFG_METHODS:
                errors.append(
                    f"{path}:{lineno}: cfg.{attr} reads a flag that is not "
                    "registered in core/config.py defaults"
                )

    if errors:
        print("\n".join(errors))
        return 1
    print(
        f"check_kernel_fallbacks: {len(kernel_modules)} kernel modules with "
        f"fallbacks, {len(flags)} registered flags, all cfg reads resolve"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
