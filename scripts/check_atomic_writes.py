#!/usr/bin/env python
"""Static check: state-persisting writes must be atomic.

Any ``open(path, "w")`` / ``open(path, "wb")`` under ``ray_tpu/train/``
or in ``ray_tpu/core/gcs.py`` persists state another process (or a
post-crash restart) will read back — checkpoints, manifests, preemption
flag files, GCS snapshots. A direct write can be torn by a crash or a
preemption mid-write, which is exactly the corruption the verified
checkpoint layer exists to catch; writers must never CREATE that state.

Rule: every such open must go through the tmp-file + ``os.replace``
commit pattern. Heuristics accepted as compliant:

- the path expression mentions ``tmp`` (``tmp = path + ".tmp"`` staging), or
- an ``os.replace(`` appears within a few lines after the open, or
- the line carries an explicit ``# atomic-ok: <why>`` waiver.

Exits non-zero listing violations; run by tier-1 via
tests/test_train_preemption.py (next to check_typed_errors.py and
check_metrics_names.py).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_OPEN_WRITE = re.compile(r"""open\(\s*([^,)]+),\s*(?:mode\s*=\s*)?["']wb?["']""")
_WAIVER = re.compile(r"#\s*atomic-ok:")
_REPLACE_WINDOW = 8  # lines after the open() in which os.replace must appear


def check_file(path: Path):
    errors = []
    lines = path.read_text().splitlines()
    for lineno, line in enumerate(lines, 1):
        m = _OPEN_WRITE.search(line)
        if m is None:
            continue
        if _WAIVER.search(line):
            continue
        path_expr = m.group(1)
        if "tmp" in path_expr.lower():
            continue  # staged write: the os.replace commit is the contract
        tail = "\n".join(lines[lineno - 1: lineno - 1 + _REPLACE_WINDOW])
        if "os.replace(" in tail:
            continue
        errors.append(
            f"{path}:{lineno}: non-atomic state write "
            f"(open({path_expr.strip()}, 'w'/'wb') without tmp + os.replace); "
            f"stage to a .tmp sibling and os.replace, or waive with "
            f"'# atomic-ok: <why>'"
        )
    return errors


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else (
        Path(__file__).resolve().parent.parent / "ray_tpu"
    )
    targets = sorted((root / "train").rglob("*.py"))
    targets.append(root / "core" / "gcs.py")
    errors = []
    for path in targets:
        errors.extend(check_file(path))
    for err in errors:
        print(f"check_atomic_writes: {err}", file=sys.stderr)
    if errors:
        print(f"check_atomic_writes: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_atomic_writes: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
