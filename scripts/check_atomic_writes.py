#!/usr/bin/env python
"""Thin compatibility shim over scripts/raylint (rule: atomic-writes).

The logic lives in scripts/raylint/rules_legacy.py; this entry point
keeps the historical CLI (`python scripts/check_atomic_writes.py
[root]`) and module API (check_file) for existing tier-1 wiring.
Repo-wide enforcement runs through `python -m scripts.raylint`
(tests/test_raylint.py).
"""

from __future__ import annotations

import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from scripts.raylint.rules_legacy import check_file  # noqa: E402,F401 - compat API


def main(argv) -> int:
    root = Path(argv[1]) if len(argv) > 1 else _REPO / "ray_tpu"
    targets = sorted((root / "train").rglob("*.py"))
    gcs = root / "core" / "gcs.py"
    if gcs.exists():
        targets.append(gcs)
    errors = []
    for path in targets:
        errors.extend(check_file(path))
    for err in errors:
        print(f"check_atomic_writes: {err}", file=sys.stderr)
    if errors:
        print(f"check_atomic_writes: {len(errors)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_atomic_writes: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
