"""Headline benchmark: GPT-2 124M training tokens/sec on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no absolute tokens/sec (BASELINE.md — scalability
envelope only), so vs_baseline is measured MFU / 0.40: the ratio of this
framework's model-flops utilization to a 40% MFU reference point, which is
strong torch-GPU-stack territory for this model class. >1.0 beats it.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

BATCH = 24  # measured best on v5e: 120.2k tok/s vs 115.8k at 16; 32 regresses (HBM pressure)
SEQ = 1024
WARMUP_STEPS = 3
MEASURE_STEPS = 20


def ring_kernel_bench() -> dict:
    """Fused-Pallas vs einsum ring-attention LOCAL BLOCK on the real
    chip (the long-context kernel claim, runnable single-chip: the ring
    collective is free under XLA; the per-step kernel is what differs).
    Same chained-inside-one-jit methodology as the train bench — per
    -call timing through the tunnel measures RTT, not compute."""
    from ray_tpu.ops.attention import flash_attention_with_lse

    b, h, s, d = 4, 8, 2048, 128
    n_iters = 40
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16) for kk in keys)

    def einsum_block(q, k, v):
        s_ = jnp.einsum(
            "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / (d ** 0.5)
        m = jnp.max(s_, axis=-1, keepdims=True)
        p = jnp.exp(s_ - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) / l

    def chained(block):
        def f(q, k, v):
            def body(_, qq):
                return block(qq, k, v).astype(jnp.bfloat16)
            return jnp.sum(
                jax.lax.fori_loop(0, n_iters, body, q).astype(jnp.float32)
            )
        return jax.jit(f)

    fused = chained(lambda q, k, v: flash_attention_with_lse(q, k, v)[0])
    ein = chained(einsum_block)

    def bench(fn):
        float(fn(q, k, v))  # compile + sync
        t0 = time.perf_counter()
        float(fn(q, k, v))  # host read = true sync
        return (time.perf_counter() - t0) / n_iters * 1e3

    fused_ms, ein_ms = bench(fused), bench(ein)
    return {
        "ring_fused_block_ms": round(fused_ms, 3),
        "ring_einsum_block_ms": round(ein_ms, 3),
        "ring_fused_speedup": round(ein_ms / fused_ms, 2),
    }


def attn_kernel_bench() -> dict:
    """Per-layer flash-attention microbench at the bench model's exact
    attention shape (B24 H12 S1024 D64 causal bf16) — the kernel the round-5
    trace showed 5-6x off roofline. Chained-inside-one-jit methodology (per
    -call timing through the tunnel measures RTT, not compute). Reports the
    auto-resolved kernel (pipelined when cfg.attn_pipeline is on, on TPU)
    and its distance to the matmul roofline, tracked every round."""
    from ray_tpu.ops.attention import _resolve_impl, flash_attention
    from ray_tpu.util import profiling as prof

    b, h, s, d = BATCH, 12, SEQ, 64
    n_fwd, n_bwd = 20, 8
    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16) for kk in keys)
    impl = _resolve_impl(None)

    def chain(n):
        def f(q, k, v):
            def body(_, qq):
                return flash_attention(qq, k, v, causal=True).astype(jnp.bfloat16)
            return jnp.sum(
                jax.lax.fori_loop(0, n, body, q).astype(jnp.float32)
            )
        return f

    fwd = jax.jit(chain(n_fwd))
    grad = jax.jit(jax.value_and_grad(chain(n_bwd), argnums=(0, 1, 2)))

    def bench(fn, sync):
        sync(fn(q, k, v))  # compile + device-read sync
        t0 = time.perf_counter()
        sync(fn(q, k, v))
        return time.perf_counter() - t0

    fwd_ms = bench(fwd, float) / n_fwd * 1e3
    grad_s = bench(grad, lambda r: float(r[0]))
    bwd_ms = max(grad_s / n_bwd * 1e3 - fwd_ms, 0.0)

    # matmul roofline: causal fwd = 2*B*H*S^2*D flops (QK^T + PV, half the
    # square), bwd = 2.5x fwd (s recompute + dv/dp/dk/dq)
    peak = prof.device_peaks(jax.devices()[0])["peak_flops"]
    fwd_flops = 2.0 * b * h * s * s * d
    roofline_ms = (fwd_flops + 2.5 * fwd_flops) / peak * 1e3
    measured_ms = fwd_ms + bwd_ms
    return {
        "attn_impl": impl,
        "attn_fwd_ms": round(fwd_ms, 3),
        "attn_bwd_ms": round(bwd_ms, 3),
        "attn_roofline_fraction": round(roofline_ms / max(measured_ms, 1e-9), 4),
    }


def _dp_sync_fields(n_params: int, n_dp: int) -> dict:
    """The data-parallel sync mode + per-replica wire bytes the current
    config flags imply, tracked in the BENCH line every round (0 bytes on
    the single-chip bench; the multichip dryrun exercises the real path)."""
    from ray_tpu.core.config import cfg
    from ray_tpu.parallel.collectives import dp_sync_bytes

    explicit = (cfg.dp_shard_update or cfg.dp_allreduce_dtype == "int8") and n_dp > 1
    mode = (
        cfg.dp_allreduce_dtype + ("+shard_update" if cfg.dp_shard_update else "")
        if explicit else "xla_psum"
    )
    return {
        "dp_sync_mode": mode,
        "dp_sync_bytes": dp_sync_bytes(
            n_params, n_dp, mode=cfg.dp_allreduce_dtype,
            shard_update=cfg.dp_shard_update, block=cfg.dp_quant_block,
        ),
    }


def _collect_telemetry(step, state, batch, n_steps: int = 5) -> dict:
    """Per-step latency histogram + node stats riding along with the
    headline number, so BENCH_*.json rounds carry telemetry instead of
    a single scalar. Separately-synced steps (outside the throughput
    window — a per-step device sync would skew it)."""
    from ray_tpu.core.stats import sample_process_rss_bytes, sample_tpu_stats
    from ray_tpu.util.metrics import get_or_create_histogram, registry

    hist = get_or_create_histogram(
        "raytpu_bench_step_seconds",
        "Wall-clock duration of individually synced benchmark steps.",
        boundaries=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
    )
    durations = []
    for _ in range(n_steps):
        t0 = time.perf_counter()
        state, metrics = step(state, batch)
        float(metrics["loss"])  # device read = true sync
        durations.append(time.perf_counter() - t0)
        hist.observe(durations[-1])
    ((_, data),) = hist.collect()
    return {
        "step_seconds": {
            "mean": round(sum(durations) / len(durations), 5),
            "min": round(min(durations), 5),
            "max": round(max(durations), 5),
            "count": data["count"],
            "buckets": [[b, c] for b, c in data["buckets"]],
        },
        "node": {
            "rss_bytes": sample_process_rss_bytes(),
            "tpu": sample_tpu_stats(),
        },
        # the full exposition is greppable from the round artifacts
        "metrics_names": sorted(
            {line.split(" ", 3)[2]
             for line in registry().prometheus_text().splitlines()
             if line.startswith("# TYPE ")}
        ),
    }


def _goodput_block(acct) -> dict:
    """The BENCH JSON `goodput` block: bucket seconds + goodput fraction
    from the same accountant/gauges the train controller publishes
    (util/goodput) — wall-time attribution rides every round."""
    report = acct.report()
    return {
        "wall_time_s": report["wall_time_s"],
        "buckets": {
            b: s for b, s in report["buckets"].items() if s > 0.0
        },
        "goodput_s": report["goodput_s"],
        "goodput_fraction": report["goodput_fraction"],
    }


def step_forensics_overhead_bench() -> dict:
    """Recorder overhead A/B (the train-side mirror of bench_serve's
    forensics bench): the SAME LMTrainer loop on the tiny model with the
    step-phase recorder off, then on at the default sampling rate.
    Emits the tokens/s ratio — the acceptance bar is >= 0.98, i.e. the
    sampled `block_until_ready` syncs plus the mark ring cost under 2%
    of throughput."""
    import numpy as np

    from ray_tpu.core.config import cfg
    from ray_tpu.models import get_config
    from ray_tpu.train import steplog
    from ray_tpu.train.trainer import LMTrainer

    n_steps = 64
    b, s = 8, 128
    config = get_config("gpt2-tiny")
    trainer = LMTrainer(config, learning_rate=1e-3, total_steps=4 + 2 * n_steps)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, config.vocab_size, size=(b, s + 1), dtype=np.int32)

    def run(tag: str) -> float:
        t0 = time.perf_counter()
        trainer.train(({"tokens": tokens} for _ in range(n_steps)),
                      num_steps=n_steps, report_every=n_steps,
                      run_name=f"bench-forensics-{tag}")
        jax.block_until_ready(trainer.state)
        return n_steps * b * s / (time.perf_counter() - t0)

    # warm the step compile AND the report path's cost-analysis cache so
    # both timed sides pay neither
    trainer.train(({"tokens": tokens} for _ in range(4)), num_steps=4,
                  report_every=2, run_name="bench-forensics-warmup")
    steplog.log().clear()
    cfg.set(train_step_log=False)
    try:
        off_tps = run("off")
        cfg.set(train_step_log=True)  # default sampling rate
        sample_every = cfg.step_log_sample_every
        on_tps = run("on")
        stats = steplog.log().stats()
    finally:
        cfg.reset()
    ratio = on_tps / off_tps
    return {
        "metric": "train_step_forensics_tokens_per_s_ratio",
        "value": round(ratio, 4),
        "unit": "ratio",
        "within_2pct": ratio >= 0.98,
        "tokens_per_s_recorder_off": round(off_tps, 1),
        "tokens_per_s_recorder_on": round(on_tps, 1),
        "sample_every": sample_every,
        "steps_per_side": n_steps,
        "marks_recorded": stats["buffered_marks"],
        "steps_indexed": stats["indexed_steps"],
    }


def main() -> None:
    from ray_tpu.models import count_params, get_config
    from ray_tpu.parallel import MeshSpec, build_mesh
    from ray_tpu.train import create_train_state, default_optimizer, make_train_step
    from ray_tpu.util.goodput import GoodputAccountant

    acct = GoodputAccountant("bench")
    acct.begin("init")

    # full layer-unroll measured fastest on-chip at this size (+15% over
    # scan: XLA fuses/overlaps across layer boundaries)
    config = get_config("gpt2-small").replace(scan_unroll=12)
    devices = jax.devices()
    mesh = build_mesh(MeshSpec(), devices=devices[:1])
    opt = default_optimizer(3e-4, total_steps=1000)
    state, shardings = create_train_state(config, opt, jax.random.PRNGKey(0), mesh)
    step = make_train_step(config, opt, mesh, state_shardings=shardings)
    n_params = count_params(state.params)

    batch = {
        "tokens": jax.random.randint(
            jax.random.PRNGKey(1), (BATCH, SEQ + 1), 0, config.vocab_size
        )
    }

    acct.begin("compile")  # warmup = compile + first dispatches
    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    float(metrics["loss"])  # value fetch: block_until_ready is unreliable
    # on tunneled-TPU platforms, so sync via an actual device read

    acct.begin("step_compute")
    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    elapsed = time.perf_counter() - t0
    acct.finish()

    tokens_per_sec = MEASURE_STEPS * BATCH * SEQ / elapsed
    step_time_s = elapsed / MEASURE_STEPS
    device_kind = getattr(devices[0], "device_kind", "unknown")
    # Cost-analysis accounting (util/profiling): the compiled step's own
    # FLOPs/bytes over the measured step time, priced against the
    # detected chip's peaks — no more hand-maintained 6ND/peak constants.
    # Must run BEFORE _collect_telemetry (which donates `state` away).
    from ray_tpu.util import profiling as prof

    try:
        cost = prof.step_cost(step, state, batch)
        roof = prof.roofline(cost, step_time_s)
        mfu = roof["mfu"]
        peak = cost.peak_flops
        profiling_block = {
            "source": "cost_analysis",
            "mfu": round(mfu, 4),
            "flops_per_step": cost.total_flops,
            "flops_per_token": round(cost.total_flops / (BATCH * SEQ), 1),
            "roofline": {
                "compute": round(mfu, 4),
                "hbm": round(roof["hbm_fraction"], 4),
                "bound": roof["bound"],
                "estimated_peaks": roof["estimated_peaks"],
            },
            "top_cost_buckets": [
                [k, v] for k, v in cost.top_buckets(5)
            ],
        }
    except Exception as exc:  # noqa: BLE001 - the headline must still print
        # degraded path: the 6ND matmul formula against the peak table
        flops_per_token = 6 * n_params
        peaks = prof.device_peaks(devices[0])
        peak = peaks["peak_flops"]
        mfu = tokens_per_sec * flops_per_token / peak
        profiling_block = {
            "source": "6nd_fallback",
            "mfu": round(mfu, 4),
            "error": repr(exc),
        }
    try:
        telemetry = _collect_telemetry(step, state, batch)
    except Exception:  # noqa: BLE001 - the headline number must still print
        telemetry = {}
    try:
        ring = ring_kernel_bench()
    except Exception:  # noqa: BLE001 - the headline number must still print
        ring = {}
    try:
        attn = attn_kernel_bench()
    except Exception:  # noqa: BLE001 - the headline number must still print
        attn = {}
    try:
        dp_sync = _dp_sync_fields(n_params, mesh.shape.get("dp", 1))
    except Exception:  # noqa: BLE001 - the headline number must still print
        dp_sync = {}
    try:
        goodput = _goodput_block(acct)
    except Exception:  # noqa: BLE001 - the headline number must still print
        goodput = {}
    try:
        # training-forensics rider: the recorder-overhead A/B tracked
        # every round next to the headline number
        step_forensics = step_forensics_overhead_bench()
    except Exception as exc:  # noqa: BLE001 - headline must still print
        step_forensics = {"error": repr(exc)}
    print(
        json.dumps(
            {
                "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.40, 3),
                # auditability: which chip the peak-FLOPs attribution used
                "device_kind": device_kind,
                "peak_flops": peak,
                "mfu": round(mfu, 4),
                "batch": BATCH,
                "seq": SEQ,
                "profiling": profiling_block,
                "goodput": goodput,
                "step_forensics": step_forensics,
                "telemetry": telemetry,
                **ring,
                **attn,
                **dp_sync,
            }
        )
    )


if __name__ == "__main__":
    import sys

    if "--step-forensics-overhead" in sys.argv[1:]:
        # standalone recorder A/B (one BENCH JSON line), CPU-runnable
        print(json.dumps(step_forensics_overhead_bench()))
    else:
        main()
