"""Spot-fleet cluster drill: the capacity plane closing the loop.

A 2-worker training gang and a serve deployment share ONE autoscaled
spot cluster: every worker node exists because the CapacityAutoscaler
aggregated demand (gang bundles, replica actors) and launched it.
Scheduled preemptions with warning windows then reclaim BOTH fleets'
nodes, one after the other:

- the training gang emergency-checkpoints inside the warning window and
  re-meshes onto replacement capacity that was pre-provisioned BEFORE
  the old node died, finishing with `max_failures=0` (only the
  preemption budget is consumed);
- serve rides its node's reclaim through replica restarts on the
  replacement, surfacing only TYPED errors to the open client loop;
- the whole episode reconstructs from one `state.postmortem()` bundle:
  `preempt.announced` -> `autoscaler.replace` -> `node.dead` per victim,
  and the run's wall time fully attributed to goodput buckets.

One JSON line reports the episode; it is also self-captured as the next
BENCH_CLUSTER_r<NN>.json round file.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import threading
import time


def _emit_result(payload: dict, rc: int = 0) -> None:
    """Print the ONE result line and self-capture it as the next
    BENCH_CLUSTER_r<NN>.json round file (same {n, cmd, rc, tail, parsed}
    shape the driver writes for bench.py), anchored to the repo root so
    the round history survives whatever cwd the bench ran from."""
    line = json.dumps(payload)
    print(line)
    root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(os.path.basename(p)[len("BENCH_CLUSTER_r"):-len(".json")])
        for p in glob.glob(os.path.join(root, "BENCH_CLUSTER_r*.json"))
        if os.path.basename(p)[len("BENCH_CLUSTER_r"):-len(".json")].isdigit()
    ]
    n = max(rounds, default=0) + 1
    path = os.path.join(root, f"BENCH_CLUSTER_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump(
            {
                "n": n,
                "cmd": "python " + " ".join(sys.argv),
                "rc": rc,
                "tail": line + "\n",
                "parsed": payload,
            },
            f,
        )
        f.write("\n")


def _first_ts(evs, kind, **match):
    for e in evs:
        if e.get("kind") != kind:
            continue
        extra = e.get("extra") or {}
        if all(extra.get(k) == v for k, v in match.items()):
            return e["ts"]
    return None


def _ordered(evs, victim_hex):
    """preempt.announced -> autoscaler.replace -> node.dead for one
    reclaimed node, on the bundle's shared wall clock."""
    announced = _first_ts(
        [e for e in evs if e.get("node") == victim_hex], "preempt.announced"
    )
    replace = _first_ts(evs, "autoscaler.replace", replaces=victim_hex)
    dead = _first_ts(
        [e for e in evs if e.get("node") == victim_hex], "node.dead"
    )
    if None in (announced, replace, dead):
        return False
    return announced <= replace <= dead


def run_head_outage(args) -> None:
    """Head fault-tolerance drill: chaos SIGKILLs the HEAD out of its own
    snapshot loop while (a) a KV writer keeps committing state, (b) task
    traffic keeps dispatching to a worker agent, and (c) a stateful
    "trainer" actor keeps stepping on that agent. The head restarts with
    --restore on the same port; the drill passes when every ACKNOWLEDGED
    write is still readable, no client surfaced an untyped error, a
    pre-restart writer is epoch-fenced, and the agent (and the actor in
    it) rode through without a process restart. Reports
    recovery-time-to-ready: head death -> first acknowledged write
    against the restored head."""
    import signal
    import socket
    import subprocess

    from ray_tpu.core.exceptions import RayTpuError, StaleEpochError
    from ray_tpu.core.gcs_service import GcsClient

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    address = f"127.0.0.1:{port}"
    workdir = tempfile.mkdtemp(prefix="bench_head_outage_")
    snap = os.path.join(workdir, "gcs.snap")
    base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                "RAY_TPU_NODE_HEARTBEAT_S": "0.2",
                "RAY_TPU_NODE_STALE_S": "2.5",
                "RAY_TPU_GCS_SNAPSHOT_INTERVAL_S": "0.5"}
    base_env.pop("RAY_TPU_CHAOS", None)
    chaos_env = {**base_env, "RAY_TPU_CHAOS":
                 f"kill_head=1,delay_s={args.outage_delay_s},"
                 "max_injections=1"}

    def spawn(cmd, log_path, env, mode="w"):
        return subprocess.Popen(cmd, env=env, stdout=open(log_path, mode),
                                stderr=subprocess.STDOUT, text=True)

    def wait_line(log_path, needle, timeout=90, proc=None):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if proc is not None and proc.poll() is not None:
                break
            with open(log_path) as f:
                if needle in f.read():
                    return
            time.sleep(0.2)
        with open(log_path) as f:
            raise AssertionError(f"never saw {needle!r} in:\n{f.read()}")

    head_cmd = [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
                "--head", "--port", str(port), "--num-cpus", "1",
                "--snapshot-path", snap]
    head = spawn(head_cmd, os.path.join(workdir, "head.log"), chaos_env)
    agent = None
    rc = 1
    acked: list = []
    writer_errors: list = []
    traffic_ok = [0]
    traffic_ok_during_outage = [0]
    traffic_typed: list = []
    traffic_untyped: list = []
    stop = threading.Event()
    outage = threading.Event()

    import ray_tpu

    try:
        wait_line(os.path.join(workdir, "head.log"), "head up", proc=head)
        agent = spawn(
            [sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
             "--address", address, "--num-cpus", "2",
             "--resources", '{"drill": 2}'],
            os.path.join(workdir, "agent.log"), base_env)
        wait_line(os.path.join(workdir, "agent.log"), "joined", proc=agent)

        ray_tpu.init(address=address, num_cpus=0, detect_accelerators=False)
        deadline = time.monotonic() + 60
        while ray_tpu.cluster_resources().get("drill", 0) < 2:
            assert time.monotonic() < deadline, (
                f"agent resources never appeared: "
                f"{ray_tpu.cluster_resources()}")
            time.sleep(0.2)

        @ray_tpu.remote(num_cpus=0, resources={"drill": 1})
        def echo(x):
            return f"ok-{x}"

        @ray_tpu.remote(num_cpus=0, resources={"drill": 1})
        class Trainer:
            def __init__(self):
                self.step_count = 0

            def step(self):
                import os as _os
                self.step_count += 1
                return {"step": self.step_count, "pid": _os.getpid()}

        trainer = Trainer.remote()
        pre = ray_tpu.get(trainer.step.remote(), timeout=60)
        assert ray_tpu.get(echo.remote(0), timeout=60) == "ok-0"

        def writer():
            # the retry window spans kill + restore: every put either
            # acks or retries invisibly; ANY surfaced error fails the
            # drill (acked writes are the durability ledger)
            c = GcsClient(address, retry_window_s=90.0)
            c.adopt_epoch()
            i = 0
            while not stop.is_set():
                try:
                    if c.kv_put(f"w{i}", {"i": i}, namespace="bench"):
                        acked.append(i)
                except Exception as exc:  # noqa: BLE001 - the verdict
                    writer_errors.append(exc)
                i += 1
                time.sleep(0.05)

        def traffic():
            # data-plane traffic: dispatch goes DIRECT to the node agent,
            # so requests should keep succeeding while the head is down;
            # any failure must at least be TYPED
            i = 1
            while not stop.is_set():
                try:
                    assert ray_tpu.get(echo.remote(i), timeout=20) == f"ok-{i}"
                    traffic_ok[0] += 1
                    if outage.is_set():
                        traffic_ok_during_outage[0] += 1
                except RayTpuError as exc:
                    traffic_typed.append(exc)
                except Exception as exc:  # noqa: BLE001 - the verdict
                    traffic_untyped.append(exc)
                i += 1
                time.sleep(0.05)

        threads = [threading.Thread(target=writer, daemon=True),
                   threading.Thread(target=traffic, daemon=True)]
        for t in threads:
            t.start()

        zombie = GcsClient(address, retry_window_s=45.0)
        epoch_before = zombie.adopt_epoch()
        zombie.pin_epoch(epoch_before)

        # chaos fires outage_delay_s after the head armed it at init
        head.wait(timeout=120)
        assert head.returncode == 137, \
            f"head should die by chaos, got rc={head.returncode}"
        t_dead = time.monotonic()
        outage.set()
        acked_at_death = len(acked)
        assert agent.poll() is None, "agent must survive the head kill"

        head = spawn(head_cmd + ["--restore"],
                     os.path.join(workdir, "head2.log"), base_env)
        wait_line(os.path.join(workdir, "head2.log"), "head up", proc=head)

        probe = GcsClient(address, retry_window_s=45.0)
        ready_deadline = time.monotonic() + 60
        while probe.kv_get("w0", namespace="bench") is None:
            assert time.monotonic() < ready_deadline, "restore never ready"
            time.sleep(0.05)
        recovery_ready_s = time.monotonic() - t_dead
        outage.clear()

        # let post-recovery traffic accumulate, then settle the ledger
        deadline = time.monotonic() + 30
        while len(acked) <= acked_at_death + 10 and \
                time.monotonic() < deadline:
            time.sleep(0.1)
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)

        missing = [i for i in acked
                   if probe.kv_get(f"w{i}", namespace="bench") is None]
        fenced = False
        try:
            zombie.kv_put("zombie", 1, namespace="bench")
        except StaleEpochError:
            fenced = True
        epoch_after = probe.head_info()["epoch"]

        post = ray_tpu.get(trainer.step.remote(), timeout=60)
        trainer_rode_through = (post["pid"] == pre["pid"]
                                and post["step"] > pre["step"])

        ok = (
            not missing
            and not writer_errors
            and not traffic_untyped
            and len(acked) > acked_at_death + 10
            and fenced and epoch_after > epoch_before
            and trainer_rode_through
            and agent.poll() is None
        )
        rc = 0 if ok else 1
        _emit_result({
            "metric": "head_outage_recovery_ready_s",
            "value": round(recovery_ready_s, 3),
            "unit": "seconds",
            "vs_baseline": 0.0,
            "passed": ok,
            "drill": "head_outage",
            "acked_writes": len(acked),
            "acked_writes_at_death": acked_at_death,
            "acked_writes_lost": len(missing),
            "writer_errors": len(writer_errors),
            "traffic_ok": traffic_ok[0],
            "traffic_ok_during_outage": traffic_ok_during_outage[0],
            "traffic_typed_errors": len(traffic_typed),
            "traffic_untyped_errors": len(traffic_untyped),
            "stale_writer_fenced": fenced,
            "epoch_before": epoch_before,
            "epoch_after": epoch_after,
            "trainer_rode_through": trainer_rode_through,
            "trainer_steps": post["step"],
            "agent_survived": agent.poll() is None,
            "wal": probe.head_info().get("wal"),
        }, rc)
    finally:
        stop.set()
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        for proc in (head, agent):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
    sys.exit(rc)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--drill", choices=("spot_fleet", "head_outage"),
                    default="spot_fleet",
                    help="spot_fleet: autoscaled preemption episode; "
                    "head_outage: chaos head SIGKILL + WAL restore")
    ap.add_argument("--steps", type=int, default=60,
                    help="training steps per run")
    ap.add_argument("--workers", type=int, default=2,
                    help="training gang size (one spot node per worker)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="serve replicas (they share one spot node)")
    ap.add_argument("--warning-s", type=float, default=2.0,
                    help="preemption warning window")
    ap.add_argument("--outage-delay-s", type=float, default=8.0,
                    help="head_outage: seconds after head start when "
                    "chaos kills it")
    args = ap.parse_args()

    if args.drill == "head_outage":
        run_head_outage(args)
        return

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.capacity import (
        CapacityAutoscaler, FakeNodeProvider, NodeType, SpotNodeProvider,
    )
    from ray_tpu.core.exceptions import RayTpuError
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )
    from ray_tpu.util import state
    from ray_tpu.util.events import events
    from ray_tpu.util.postmortem import load_bundle

    workdir = tempfile.mkdtemp(prefix="bench_cluster_")
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    scaler = None
    rc = 1
    try:
        events().clear()
        provider = SpotNodeProvider(FakeNodeProvider(rt.scheduler),
                                    warning_s=args.warning_s)
        scaler = CapacityAutoscaler(
            rt.scheduler, provider,
            [
                NodeType("spot-train", {"CPU": 1.0, "trainer": 1.0},
                         capacity_class="spot"),
                NodeType("spot-serve",
                         {"CPU": float(args.replicas),
                          "serve_slot": float(args.replicas)},
                         capacity_class="spot"),
            ],
            poll_interval_s=0.05, idle_timeout_s=60.0, runtime=rt,
        )
        scaler.start()

        @serve.deployment(num_replicas=args.replicas,
                          resources_per_replica={"CPU": 1.0,
                                                 "serve_slot": 1.0})
        class Echo:
            def __call__(self, x):
                return f"ok-{x}"

        handle = serve.run(Echo.bind(), name="fleet-echo")
        assert ray_tpu.get(handle.remote(0), timeout=60) == "ok-0"

        total_steps = args.steps

        def train_fn(config):
            from ray_tpu import train

            ctx = train.get_context()
            ckpt = train.get_checkpoint()
            start = int(ckpt["step"]) + 1 if ckpt is not None else 0
            for step in range(start, total_steps):
                time.sleep(0.02)
                if ctx.world_rank != 0:
                    if train.is_preempted():
                        return "preempted"
                    continue
                if train.should_checkpoint():
                    train.report({"step": step}, checkpoint={"step": step},
                                 checkpoint_step=step)
                elif train.is_preempted():
                    return "preempted"
                elif step % 10 == 9:
                    train.report({"step": step}, checkpoint={"step": step},
                                 checkpoint_step=step)
                else:
                    train.report({"step": step})
            return "done"

        controller = TrainController(
            train_fn,
            ScalingConfig(num_workers=args.workers,
                          resources_per_worker={"CPU": 1.0, "trainer": 1.0}),
            RunConfig(name="fleet-train",
                      storage_path=os.path.join(workdir, "trial"),
                      failure=FailureConfig(max_failures=0)),
            train_config={},
            restart_backoff_s=0.0,
        )
        box = {}
        thread = threading.Thread(
            target=lambda: box.update(result=controller.run()), daemon=True
        )
        thread.start()

        serve_ok = [0]
        serve_errors: list = []
        stop_serving = threading.Event()

        def client_loop():
            i = 1
            while not stop_serving.is_set():
                try:
                    out = ray_tpu.get(handle.remote(i), timeout=30)
                    assert out == f"ok-{i}"
                    serve_ok[0] += 1
                except Exception as exc:  # noqa: BLE001 - tallied, typedness checked below
                    serve_errors.append(exc)
                i += 1
                time.sleep(0.05)

        client = threading.Thread(target=client_loop, daemon=True)
        client.start()

        deadline = time.monotonic() + 60
        while not controller.metrics_history and time.monotonic() < deadline:
            time.sleep(0.02)
        assert controller.metrics_history, "gang never started reporting"

        # ---- preemption 1: a gang-hosting train node
        train_victim = next(
            n for n in rt.scheduler.nodes()
            if n.labels.get("node_type") == "spot-train"
            and rt.scheduler.resident_bundles(n.node_id.hex())
        )
        provider.preempt_after(train_victim, 0.01, warning_s=args.warning_s)

        thread.join(timeout=180)
        assert not thread.is_alive(), "controller never finished"
        result = box["result"]

        # ---- preemption 2: the serve node; replicas must come back
        serve_victim = next(
            n for n in rt.scheduler.nodes()
            if n.labels.get("node_type") == "spot-serve" and n.alive
        )
        provider.preempt_after(serve_victim, 0.01, warning_s=args.warning_s)
        deadline = time.monotonic() + 30
        while serve_victim.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not serve_victim.alive, "serve node never reclaimed"
        # recovered = replicas live again AND a fresh request round-trips
        recovered = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            status = serve.status().get("fleet-echo", {})
            if status.get("live_replicas", 0) >= args.replicas:
                try:
                    if ray_tpu.get(handle.remote("post"),
                                   timeout=10) == "ok-post":
                        recovered = True
                        break
                except RayTpuError:
                    pass
            time.sleep(0.1)
        stop_serving.set()
        client.join(timeout=30)

        # the train victim's reclaim also has to land before we bundle
        deadline = time.monotonic() + 30
        while train_victim.alive and time.monotonic() < deadline:
            time.sleep(0.05)

        untyped = [e for e in serve_errors if not isinstance(e, RayTpuError)]

        # ---- one bundle reconstructs the whole episode
        bundle_path = os.path.join(workdir, "episode.tgz")
        state.postmortem(bundle_path, note="spot-fleet bench drill")
        evs = load_bundle(bundle_path)["events.jsonl"]
        train_order_ok = _ordered(evs, train_victim.node_id.hex())
        serve_order_ok = _ordered(evs, serve_victim.node_id.hex())

        goodput = result.goodput or {}
        buckets = goodput.get("buckets", {})
        ok = (
            result.status == RunStatus.FINISHED
            and result.num_preempt_restarts == 1
            and scaler.stats["replacements"] >= 2
            and train_order_ok and serve_order_ok
            and recovered and not untyped
        )
        rc = 0 if ok else 1
        _emit_result({
            "metric": "cluster_spot_fleet_goodput_fraction",
            "value": round(goodput.get("goodput_fraction", 0.0), 3),
            "unit": "fraction",
            "vs_baseline": 0.0,
            "passed": ok,
            "train_status": str(result.status),
            "steps": total_steps,
            "workers": args.workers,
            "num_preempt_restarts": result.num_preempt_restarts,
            "max_failures_burned": 0 if result.status == RunStatus.FINISHED
            else 1,
            "preemptions": provider.num_preemptions(),
            "warning_s": args.warning_s,
            "scale_ups": scaler.stats["scale_ups"],
            "scale_downs": scaler.stats["scale_downs"],
            "replacements": scaler.stats["replacements"],
            "train_event_order_ok": train_order_ok,
            "serve_event_order_ok": serve_order_ok,
            "serve_recovered": recovered,
            "serve_requests_ok": serve_ok[0],
            "serve_typed_errors": len(serve_errors) - len(untyped),
            "serve_untyped_errors": len(untyped),
            "wall_time_s": round(goodput.get("wall_time_s", 0.0), 3),
            "goodput_buckets": {k: round(v, 3) for k, v in buckets.items()},
            "postmortem_bundle": bundle_path,
        }, rc)
        serve.shutdown()
    finally:
        if scaler is not None:
            scaler.stop()
        ray_tpu.shutdown()
    sys.exit(rc)


if __name__ == "__main__":
    main()
