"""Spot-fleet cluster drill: the capacity plane closing the loop.

A 2-worker training gang and a serve deployment share ONE autoscaled
spot cluster: every worker node exists because the CapacityAutoscaler
aggregated demand (gang bundles, replica actors) and launched it.
Scheduled preemptions with warning windows then reclaim BOTH fleets'
nodes, one after the other:

- the training gang emergency-checkpoints inside the warning window and
  re-meshes onto replacement capacity that was pre-provisioned BEFORE
  the old node died, finishing with `max_failures=0` (only the
  preemption budget is consumed);
- serve rides its node's reclaim through replica restarts on the
  replacement, surfacing only TYPED errors to the open client loop;
- the whole episode reconstructs from one `state.postmortem()` bundle:
  `preempt.announced` -> `autoscaler.replace` -> `node.dead` per victim,
  and the run's wall time fully attributed to goodput buckets.

One JSON line reports the episode; it is also self-captured as the next
BENCH_CLUSTER_r<NN>.json round file.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import threading
import time


def _emit_result(payload: dict, rc: int = 0) -> None:
    """Print the ONE result line and self-capture it as the next
    BENCH_CLUSTER_r<NN>.json round file (same {n, cmd, rc, tail, parsed}
    shape the driver writes for bench.py), anchored to the repo root so
    the round history survives whatever cwd the bench ran from."""
    line = json.dumps(payload)
    print(line)
    root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(os.path.basename(p)[len("BENCH_CLUSTER_r"):-len(".json")])
        for p in glob.glob(os.path.join(root, "BENCH_CLUSTER_r*.json"))
        if os.path.basename(p)[len("BENCH_CLUSTER_r"):-len(".json")].isdigit()
    ]
    n = max(rounds, default=0) + 1
    path = os.path.join(root, f"BENCH_CLUSTER_r{n:02d}.json")
    with open(path, "w") as f:
        json.dump(
            {
                "n": n,
                "cmd": "python " + " ".join(sys.argv),
                "rc": rc,
                "tail": line + "\n",
                "parsed": payload,
            },
            f,
        )
        f.write("\n")


def _first_ts(evs, kind, **match):
    for e in evs:
        if e.get("kind") != kind:
            continue
        extra = e.get("extra") or {}
        if all(extra.get(k) == v for k, v in match.items()):
            return e["ts"]
    return None


def _ordered(evs, victim_hex):
    """preempt.announced -> autoscaler.replace -> node.dead for one
    reclaimed node, on the bundle's shared wall clock."""
    announced = _first_ts(
        [e for e in evs if e.get("node") == victim_hex], "preempt.announced"
    )
    replace = _first_ts(evs, "autoscaler.replace", replaces=victim_hex)
    dead = _first_ts(
        [e for e in evs if e.get("node") == victim_hex], "node.dead"
    )
    if None in (announced, replace, dead):
        return False
    return announced <= replace <= dead


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60,
                    help="training steps per run")
    ap.add_argument("--workers", type=int, default=2,
                    help="training gang size (one spot node per worker)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="serve replicas (they share one spot node)")
    ap.add_argument("--warning-s", type=float, default=2.0,
                    help="preemption warning window")
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.capacity import (
        CapacityAutoscaler, FakeNodeProvider, NodeType, SpotNodeProvider,
    )
    from ray_tpu.core.exceptions import RayTpuError
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )
    from ray_tpu.util import state
    from ray_tpu.util.events import events
    from ray_tpu.util.postmortem import load_bundle

    workdir = tempfile.mkdtemp(prefix="bench_cluster_")
    rt = ray_tpu.init(num_cpus=1, detect_accelerators=False)
    scaler = None
    rc = 1
    try:
        events().clear()
        provider = SpotNodeProvider(FakeNodeProvider(rt.scheduler),
                                    warning_s=args.warning_s)
        scaler = CapacityAutoscaler(
            rt.scheduler, provider,
            [
                NodeType("spot-train", {"CPU": 1.0, "trainer": 1.0},
                         capacity_class="spot"),
                NodeType("spot-serve",
                         {"CPU": float(args.replicas),
                          "serve_slot": float(args.replicas)},
                         capacity_class="spot"),
            ],
            poll_interval_s=0.05, idle_timeout_s=60.0, runtime=rt,
        )
        scaler.start()

        @serve.deployment(num_replicas=args.replicas,
                          resources_per_replica={"CPU": 1.0,
                                                 "serve_slot": 1.0})
        class Echo:
            def __call__(self, x):
                return f"ok-{x}"

        handle = serve.run(Echo.bind(), name="fleet-echo")
        assert ray_tpu.get(handle.remote(0), timeout=60) == "ok-0"

        total_steps = args.steps

        def train_fn(config):
            from ray_tpu import train

            ctx = train.get_context()
            ckpt = train.get_checkpoint()
            start = int(ckpt["step"]) + 1 if ckpt is not None else 0
            for step in range(start, total_steps):
                time.sleep(0.02)
                if ctx.world_rank != 0:
                    if train.is_preempted():
                        return "preempted"
                    continue
                if train.should_checkpoint():
                    train.report({"step": step}, checkpoint={"step": step},
                                 checkpoint_step=step)
                elif train.is_preempted():
                    return "preempted"
                elif step % 10 == 9:
                    train.report({"step": step}, checkpoint={"step": step},
                                 checkpoint_step=step)
                else:
                    train.report({"step": step})
            return "done"

        controller = TrainController(
            train_fn,
            ScalingConfig(num_workers=args.workers,
                          resources_per_worker={"CPU": 1.0, "trainer": 1.0}),
            RunConfig(name="fleet-train",
                      storage_path=os.path.join(workdir, "trial"),
                      failure=FailureConfig(max_failures=0)),
            train_config={},
            restart_backoff_s=0.0,
        )
        box = {}
        thread = threading.Thread(
            target=lambda: box.update(result=controller.run()), daemon=True
        )
        thread.start()

        serve_ok = [0]
        serve_errors: list = []
        stop_serving = threading.Event()

        def client_loop():
            i = 1
            while not stop_serving.is_set():
                try:
                    out = ray_tpu.get(handle.remote(i), timeout=30)
                    assert out == f"ok-{i}"
                    serve_ok[0] += 1
                except Exception as exc:  # noqa: BLE001 - tallied, typedness checked below
                    serve_errors.append(exc)
                i += 1
                time.sleep(0.05)

        client = threading.Thread(target=client_loop, daemon=True)
        client.start()

        deadline = time.monotonic() + 60
        while not controller.metrics_history and time.monotonic() < deadline:
            time.sleep(0.02)
        assert controller.metrics_history, "gang never started reporting"

        # ---- preemption 1: a gang-hosting train node
        train_victim = next(
            n for n in rt.scheduler.nodes()
            if n.labels.get("node_type") == "spot-train"
            and rt.scheduler.resident_bundles(n.node_id.hex())
        )
        provider.preempt_after(train_victim, 0.01, warning_s=args.warning_s)

        thread.join(timeout=180)
        assert not thread.is_alive(), "controller never finished"
        result = box["result"]

        # ---- preemption 2: the serve node; replicas must come back
        serve_victim = next(
            n for n in rt.scheduler.nodes()
            if n.labels.get("node_type") == "spot-serve" and n.alive
        )
        provider.preempt_after(serve_victim, 0.01, warning_s=args.warning_s)
        deadline = time.monotonic() + 30
        while serve_victim.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not serve_victim.alive, "serve node never reclaimed"
        # recovered = replicas live again AND a fresh request round-trips
        recovered = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            status = serve.status().get("fleet-echo", {})
            if status.get("live_replicas", 0) >= args.replicas:
                try:
                    if ray_tpu.get(handle.remote("post"),
                                   timeout=10) == "ok-post":
                        recovered = True
                        break
                except RayTpuError:
                    pass
            time.sleep(0.1)
        stop_serving.set()
        client.join(timeout=30)

        # the train victim's reclaim also has to land before we bundle
        deadline = time.monotonic() + 30
        while train_victim.alive and time.monotonic() < deadline:
            time.sleep(0.05)

        untyped = [e for e in serve_errors if not isinstance(e, RayTpuError)]

        # ---- one bundle reconstructs the whole episode
        bundle_path = os.path.join(workdir, "episode.tgz")
        state.postmortem(bundle_path, note="spot-fleet bench drill")
        evs = load_bundle(bundle_path)["events.jsonl"]
        train_order_ok = _ordered(evs, train_victim.node_id.hex())
        serve_order_ok = _ordered(evs, serve_victim.node_id.hex())

        goodput = result.goodput or {}
        buckets = goodput.get("buckets", {})
        ok = (
            result.status == RunStatus.FINISHED
            and result.num_preempt_restarts == 1
            and scaler.stats["replacements"] >= 2
            and train_order_ok and serve_order_ok
            and recovered and not untyped
        )
        rc = 0 if ok else 1
        _emit_result({
            "metric": "cluster_spot_fleet_goodput_fraction",
            "value": round(goodput.get("goodput_fraction", 0.0), 3),
            "unit": "fraction",
            "vs_baseline": 0.0,
            "passed": ok,
            "train_status": str(result.status),
            "steps": total_steps,
            "workers": args.workers,
            "num_preempt_restarts": result.num_preempt_restarts,
            "max_failures_burned": 0 if result.status == RunStatus.FINISHED
            else 1,
            "preemptions": provider.num_preemptions(),
            "warning_s": args.warning_s,
            "scale_ups": scaler.stats["scale_ups"],
            "scale_downs": scaler.stats["scale_downs"],
            "replacements": scaler.stats["replacements"],
            "train_event_order_ok": train_order_ok,
            "serve_event_order_ok": serve_order_ok,
            "serve_recovered": recovered,
            "serve_requests_ok": serve_ok[0],
            "serve_typed_errors": len(serve_errors) - len(untyped),
            "serve_untyped_errors": len(untyped),
            "wall_time_s": round(goodput.get("wall_time_s", 0.0), 3),
            "goodput_buckets": {k: round(v, 3) for k, v in buckets.items()},
            "postmortem_bundle": bundle_path,
        }, rc)
        serve.shutdown()
    finally:
        if scaler is not None:
            scaler.stop()
        ray_tpu.shutdown()
    sys.exit(rc)


if __name__ == "__main__":
    main()
