"""Streaming data plane benchmark: chaos-perturbed ingest + gang feed.

Three driver-measured phases, one BENCH JSON line (the PR 12 acceptance
numbers — nothing here is self-reported by the pipeline under test):

  A. ingest      — distributed streaming read→map over a 4-node
                   in-process cluster: rows/s, bytes/s, locality hit
                   rate. vs_baseline compares against the same plan run
                   driver-local (prefetch window 1, locality off) — the
                   pre-PR-12 iterator shape.
  B. capstone    — chaos-perturbed (delay injection on the map stage)
                   streaming_split gang feed into a 2-worker LMTrainer
                   gang via train.get_dataset_shard: input_wait
                   fraction from the goodput accountant, stall-watchdog
                   silence, rows exactly-once across the gang.
  C. spill drill — tiny object store + tiny in-flight byte budget:
                   ingest must spill (spilled_bytes > 0), in-flight
                   bytes must never exceed the budget, and the rows
                   must match the unconstrained run exactly.

Run: JAX_PLATFORMS=cpu python bench_data.py
"""

from __future__ import annotations

import json
import tempfile
import time

import numpy as np

import ray_tpu
from ray_tpu import data
from ray_tpu.core.chaos import clear_chaos, num_injected, set_chaos
from ray_tpu.data.dataset import DataContext

ROWS = 200_000
NUM_BLOCKS = 32
SEQ_LEN = 16
BATCH_SIZE = 4
TRAIN_STEPS = 8


def tokenize_block(block):
    """The "tokenizer" map stage: light compute plus ~10ms of simulated
    I/O latency per block (remote shard fetch / tokenizer service call —
    the thing an ingest stage actually waits on). The in-flight window
    overlaps these waits; a serial driver loop pays them end to end.
    The name is the chaos name_filter target in phase B."""
    time.sleep(0.01)
    toks = block["tokens"].astype(np.int64)
    acc = (toks * 6364136223846793005 + 1442695040888963407) ^ toks
    return {"tokens": (acc % 255).astype(np.int32)}


def token_dataset() -> data.Dataset:
    rng = np.random.default_rng(0)
    return data.from_numpy(
        {"tokens": rng.integers(0, 255, ROWS).astype(np.int32)},
        num_blocks=NUM_BLOCKS,
    ).map_batches(tokenize_block)


def drain(ds: data.Dataset):
    """Driver-side full consumption; returns (rows, bytes, seconds)."""
    rows = nbytes = 0
    t0 = time.perf_counter()
    for block in ds.iter_blocks():
        col = block["tokens"]
        rows += int(col.shape[0])
        nbytes += int(col.nbytes)
    return rows, nbytes, time.perf_counter() - t0


def drain_serial():
    """The pre-PR-12 shape: the driver submits one task at a time and
    materializes every block locally before touching the next — no
    in-flight window, no pipelining across stages, no consumer-side
    prefetch thread, no locality routing."""
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 255, ROWS).astype(np.int32)
    bounds = np.linspace(0, ROWS, NUM_BLOCKS + 1).astype(int)
    read = ray_tpu.remote(lambda lo, hi: {"tokens": tokens[lo:hi]})
    tok = ray_tpu.remote(tokenize_block)
    rows = 0
    t0 = time.perf_counter()
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        block = ray_tpu.get(tok.remote(ray_tpu.get(read.remote(lo, hi))))
        rows += int(block["tokens"].shape[0])
    return rows, time.perf_counter() - t0


# --------------------------------------------------------------- A: ingest


def phase_ingest():
    ray_tpu.init(num_cpus=8, num_nodes=4, detect_accelerators=False)
    try:
        base_rows, base_s = drain_serial()

        ds = token_dataset()
        rows, nbytes, took = drain(ds)
        stats = ds.stats() or {}
        assert rows == base_rows == ROWS, (rows, base_rows)
        return {
            "rows_per_s": round(rows / took, 1),
            "bytes_per_s": round(nbytes / took, 1),
            "rows": rows,
            "blocks": stats.get("blocks_consumed"),
            "locality_hit_rate": stats.get("locality_hit_rate"),
            "backpressure_stall_s": stats.get("backpressure_stall_s"),
            "baseline_rows_per_s": round(base_rows / base_s, 1),
            "speedup": round(base_s / took, 3),
        }
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------- B: capstone


def train_loop(config):
    import ray_tpu.data as rd
    from ray_tpu import train
    from ray_tpu.models import get_config
    from ray_tpu.train import LMTrainer

    shard = train.get_dataset_shard("train")
    trainer = LMTrainer(get_config("gpt2-tiny"), learning_rate=1e-3,
                        total_steps=config["steps"])
    batches = rd.lm_batch_iterator(shard, seq_len=SEQ_LEN,
                                   batch_size=BATCH_SIZE)
    trainer.train(batches, num_steps=config["steps"], report_every=2)


def phase_capstone():
    from ray_tpu.train import RunConfig, ScalingConfig, TrainController

    ray_tpu.init(num_cpus=8, num_nodes=4, detect_accelerators=False)
    try:
        ds = token_dataset()
        # perturb, don't kill: delay injection on the tokenizer stage —
        # the ingest plane must absorb jitter inside its prefetch window
        # (map tasks run with max_retries=0; the kill drill lives in
        # tests/test_data_cluster.py where lineage re-execution is the
        # point)
        set_chaos(delay_s=0.05, max_injections=12,
                  name_filter="tokenize_block", seed=3)
        try:
            controller = TrainController(
                train_loop, ScalingConfig(num_workers=2),
                RunConfig(name="bench_data_capstone"),
                {"steps": TRAIN_STEPS},
                datasets={"train": ds},
            )
            result = controller.run()
        finally:
            injected = num_injected()
            clear_chaos()
        goodput = result.goodput or {}
        stats = ds.stats() or {}
        watchdog = controller.stall_watchdog
        return {
            "status": str(result.status),
            "chaos_injected": injected,
            "input_wait_fraction": goodput.get("input_wait_fraction"),
            "goodput_fraction": goodput.get("goodput_fraction"),
            "wall_time_s": goodput.get("wall_time_s"),
            "watchdog_fired": bool(watchdog.stalled) if watchdog else False,
            "blocks_consumed": stats.get("blocks_consumed"),
            "locality_hit_rate": stats.get("locality_hit_rate"),
        }
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------- C: spill drill


def spill_dataset() -> data.Dataset:
    # 16 blocks x 32768 int32 rows = 128 KiB per block (over the 100 KiB
    # inline cutoff, so blocks are HOST-tier spill candidates), 2 MiB total
    rng = np.random.default_rng(7)
    return data.from_numpy(
        {"tokens": rng.integers(0, 255, 16 * 32768).astype(np.int32)},
        num_blocks=16,
    ).map_batches(tokenize_block)


def phase_spill(tmp_dir: str):
    # unconstrained reference rows first
    ray_tpu.init(num_cpus=4, num_nodes=2, detect_accelerators=False)
    try:
        want = sorted(
            int(r) for b in spill_dataset().iter_blocks() for r in b["tokens"]
        )
    finally:
        ray_tpu.shutdown()

    budget = 640 << 10  # ~5 blocks in flight...
    capacity = 256 << 10  # ...through a 2-block store: must spill
    ray_tpu.init(num_cpus=4, num_nodes=2, detect_accelerators=False,
                 object_store_capacity=capacity, spill_dir=tmp_dir)
    ctx = DataContext.get_current()
    saved = (ctx.target_inflight_bytes, ctx.backpressure_max_stall_s)
    ctx.target_inflight_bytes = budget
    ctx.backpressure_max_stall_s = 0.5  # spill heals pressure; bound stalls
    try:
        ds = spill_dataset()
        got = sorted(int(r) for b in ds.iter_blocks() for r in b["tokens"])
        stats = ds.stats() or {}
        return {
            "byte_budget": budget,
            "max_inflight_bytes": stats.get("max_inflight_bytes"),
            "within_budget": (stats.get("max_inflight_bytes") or 0) <= budget,
            "spilled_bytes": stats.get("spilled_bytes"),
            "spilled": (stats.get("spilled_bytes") or 0) > 0,
            "backpressure_stall_s": stats.get("backpressure_stall_s"),
            "rows_match_unconstrained": got == want,
        }
    finally:
        ctx.target_inflight_bytes, ctx.backpressure_max_stall_s = saved
        ray_tpu.shutdown()


def main():
    ingest = phase_ingest()
    capstone = phase_capstone()
    with tempfile.TemporaryDirectory() as tmp:
        spill = phase_spill(tmp)

    ok = (
        capstone["status"].endswith("FINISHED")
        and not capstone["watchdog_fired"]
        and (capstone["input_wait_fraction"] or 0.0) < 0.05
        and (ingest["locality_hit_rate"] or 0.0) >= 0.8
        and spill["spilled"]
        and spill["within_budget"]
        and spill["rows_match_unconstrained"]
    )
    print("BENCH " + json.dumps({
        "metric": "data_streaming_ingest",
        "value": ingest["rows_per_s"],
        "unit": "rows/s",
        "vs_baseline": ingest["speedup"],
        "accepted": ok,
        "ingest": ingest,
        "capstone": capstone,
        "spill_drill": spill,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
